"""Continuous-batching solver service: the resident process that turns
one-shot solve calls into a serving loop (ROADMAP item 4, the
"millions of users" path).

``api.solve_many`` (PR 4/5) batches instances *within one call*; this
module batches *across concurrent callers*, the way LLM serving does:

- an **admission queue** collects requests from any number of client
  threads / connections;
- a **tick policy** (:class:`TickPolicy`) bounds latency: a tick fires
  as soon as ``max_batch`` requests are pending OR the oldest request
  has waited ``max_wait`` seconds — so a lone request never waits more
  than one ``max_wait`` and a burst rides the vmap;
- each tick **coalesces** its requests into
  :func:`~pydcop_tpu.ops.compile.problem_group_key` buckets (after the
  same static-params partition ``api.solve_many`` uses) and dispatches
  every group as ONE ``run_many_batched`` device program — requests
  that share a bucket are the same executable with different data, so
  coalescing them is a memcpy-stack plus one warm dispatch;
- **occupancy bucketing** pads each group to a power-of-two instance
  count by repeating its last member (results discarded), so the
  vmapped runner cache — which keys on K — converges on a handful of
  executables and steady-state ticks perform ZERO XLA compiles no
  matter how ragged the traffic is
  (``tools/recompile_guard.py:run_service_guard`` pins this);
- **warm state is the point**: the chunk-runner cache
  (``engine/batched.py``), the compiled-problem cache (keyed on the
  request's dcop identity), and per-session
  :class:`~pydcop_tpu.engine.incremental.IncrementalCompiler` pins all
  persist across requests, so after the cold tick a request costs
  dispatch + memcpy, never tracing or XLA;
- **session affinity**: a client that names a ``session`` gets its
  problem pinned to an IncrementalCompiler; streaming ``set_values``
  deltas (external-variable updates) re-tabulates only the touched
  constraints on device (``compile.incremental``) — zero full
  recompiles after the first segment;
- every dispatch runs under the service's
  :class:`~pydcop_tpu.engine.supervisor.Supervisor` (PR 6), so a
  poisoned or OOM-ing request quarantines / splits instead of failing
  its batchmates, and the device-layer chaos kinds (``device_oom``,
  ``device_transient``, ``nan_inject``) exercise exactly those paths
  against a live service.

Coalesced results are bit-identical to per-request sequential
``api.solve`` calls with the same ``pad_policy`` — the per-instance
RNG-parity contract of ``run_many_batched`` (``docs/performance.md``)
— and a request that shares a tick with a poisoned batchmate still
returns the exact fault-free answer.

Wire protocol (:class:`ServiceServer` / :class:`ServiceClient`):
newline-JSON frames over TCP, the same framing as the hostnet control
plane (``infrastructure/hostnet.py``).  One request in flight per
connection; concurrency is connections — N clients on N sockets
coalesce into shared ticks.  ``pydcop_tpu serve`` is the CLI front
(``docs/serving.md`` covers the tick policy, affinity, and failure
semantics under the PR 6 recovery matrix).

Telemetry (``docs/observability.md``): counters ``service.requests``/
``service.ticks``/``service.dispatches``/``service.coalesced``/
``service.pad_instances``, histograms ``service.queue_wait_s``/
``service.latency_s``/``service.batch_occupancy``, and per-request
``service.queue-wait`` + ``service.request`` spans / per-group
``service.dispatch`` spans that ``pydcop_tpu trace-summary`` folds
into queue-wait / occupancy / latency percentiles.

This module is import-light by design: jax (and the batched engine)
load on first dispatch, not at import, so ``api.ServiceClient`` stays
usable from jax-free client processes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import socket
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Mapping, Optional, Tuple, Union

from pydcop_tpu.telemetry import get_metrics, get_tracer
from pydcop_tpu.telemetry.summary import _percentile

#: queue-wait / latency histogram buckets (seconds) — service
#: latencies live in the 1ms..10s band, below the metrics module's
#: generic defaults
_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)

#: bounded stats windows (per-service): enough for stable p99 at the
#: bench's request counts without growing forever in a resident process
_STATS_WINDOW = 8192


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


@dataclasses.dataclass
class TickPolicy:
    """When a tick fires: as soon as ``max_batch`` requests are
    pending, or as soon as the OLDEST pending request has waited
    ``max_wait`` seconds — whichever comes first.  ``max_batch`` also
    caps how many requests one tick drains (a burst beyond it rolls
    into the immediately-following tick), so dispatch width — and with
    it HBM footprint and per-tick latency — stays bounded."""

    max_batch: int = 32
    max_wait: float = 0.01

    def __post_init__(self) -> None:
        if self.max_batch < 1:
            raise ValueError(
                f"max_batch must be >= 1, got {self.max_batch}"
            )
        if self.max_wait < 0:
            raise ValueError(
                f"max_wait must be >= 0, got {self.max_wait}"
            )


class PendingResult:
    """Handle for a submitted request: :meth:`result` blocks until the
    service tick that carried the request completes (or raises what
    the dispatch raised)."""

    def __init__(self) -> None:
        self._done = threading.Event()
        self._result: Optional[Dict[str, Any]] = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                "service request still pending after "
                f"{timeout}s (is the service running?)"
            )
        if self._error is not None:
            raise self._error
        assert self._result is not None
        return self._result

    # -- service side ----------------------------------------------------

    def _set_result(self, result: Dict[str, Any]) -> None:
        self._result = result
        self._done.set()

    def _set_error(self, error: BaseException) -> None:
        self._error = error
        self._done.set()


@dataclasses.dataclass
class _Request:
    """One admitted solve request (internal)."""

    dcop: Any  # DCOP object (loaded at admission)
    dcop_key: Tuple  # compiled-problem cache key
    algo: str
    params: Dict[str, Any]  # prepared algo params
    rounds: int
    seed: int
    chunk_size: int
    convergence_chunks: int
    n_restarts: int
    timeout: Optional[float]
    session: Optional[str]
    set_values: Optional[Dict[str, Any]]
    pending: PendingResult
    enqueue_t: float = 0.0
    queue_wait: float = 0.0


class _Session:
    """One client's pinned incremental-compile state: the
    :class:`~pydcop_tpu.engine.incremental.IncrementalCompiler` built
    on the session's FIRST request plus the accumulated external
    values its ``set_values`` deltas stream in.  Segment 2+ costs a
    device delta-update (``compile.incremental``) or nothing
    (``compile.reused``) — never a host rebuild or an XLA compile."""

    def __init__(self, compiler, dcop, dcop_key: Tuple) -> None:
        self.compiler = compiler
        self.dcop = dcop
        self.dcop_key = dcop_key  # admission identity of segment 1
        self.ext_values: Dict[str, Any] = {}
        self.segments = 0


class ServiceError(RuntimeError):
    """A request the service could not solve (bad algo/params/dcop, or
    an unrecoverable dispatch failure); the message is the client-side
    report."""


class SolverService:
    """The resident continuous-batching solver (module docstring).

    In-process use::

        with session() as tel, SolverService(pad_policy="pow2") as svc:
            pendings = [svc.submit(d, "dsa", {}) for d in dcops]
            results = [p.result() for p in pendings]

    ``submit`` is thread-safe: N client threads submitting
    concurrently coalesce into shared ticks.  :class:`ServiceServer`
    puts the same object behind a TCP socket for out-of-process
    clients (:class:`ServiceClient`).

    The service does not open a telemetry session of its own —
    counters/spans land in whatever session is active (the ``serve``
    command opens one for the server's lifetime; in-process embedders
    wrap the service in ``telemetry.session()``), and the always-on
    :meth:`stats` aggregates stay available without one.
    """

    def __init__(
        self,
        pad_policy: str = "pow2",
        tick: Optional[TickPolicy] = None,
        *,
        max_batch: Optional[int] = None,
        max_wait: Optional[float] = None,
        instance_bucket: str = "pow2",
        chaos: Optional[str] = None,
        chaos_seed: int = 0,
        retry_budget: Optional[int] = None,
        chunk_floor: Optional[int] = None,
        on_numeric_fault: Optional[str] = None,
        compile_cache_max: int = 256,
        autostart: bool = True,
    ):
        from pydcop_tpu.ops.padding import as_pad_policy

        as_pad_policy(pad_policy)  # fail fast on a malformed spec
        self.pad_policy = pad_policy
        if tick is None:
            tick = TickPolicy()
        if max_batch is not None:
            tick = dataclasses.replace(tick, max_batch=max_batch)
        if max_wait is not None:
            tick = dataclasses.replace(tick, max_wait=max_wait)
        self.tick = tick
        if instance_bucket not in ("pow2", "none"):
            raise ValueError(
                "instance_bucket must be 'pow2' or 'none', got "
                f"{instance_bucket!r}"
            )
        self.instance_bucket = instance_bucket

        plan = None
        if chaos:
            from pydcop_tpu.faults import FaultPlan

            plan = FaultPlan.from_spec(chaos, chaos_seed)
            if plan.message_faults_configured or plan.crashes:
                raise ValueError(
                    "the solver service dispatches on the batched "
                    "engine, which has no message plane — chaos "
                    "accepts the DEVICE-layer kinds only: device_oom, "
                    "device_transient, nan_inject (docs/faults.md)"
                )
        self.chaos_plan = plan
        from pydcop_tpu.engine.supervisor import make_supervisor

        self._sup = make_supervisor(
            retry_budget=retry_budget, chunk_floor=chunk_floor,
            on_numeric_fault=on_numeric_fault, plan=plan,
        )

        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._closing = False
        self._worker: Optional[threading.Thread] = None
        self._sessions: Dict[str, _Session] = {}
        # compiled-problem cache: dcop identity -> CompiledProblem
        # (LRU; the value also pins the DCOP object so an id-keyed
        # entry can never alias a new object at a recycled address)
        self._compiled: "OrderedDict[Tuple, Tuple[Any, Any]]" = (
            OrderedDict()
        )
        self._compile_cache_max = compile_cache_max

        # always-on aggregates (stats()); bounded windows
        self._stats_lock = threading.Lock()
        self._n_requests = 0
        self._n_ticks = 0
        self._n_dispatches = 0
        self._n_coalesced = 0  # requests that shared a group with >= 1 other
        self._n_pad_instances = 0
        self._n_errors = 0
        self._queue_waits: deque = deque(maxlen=_STATS_WINDOW)
        self._latencies: deque = deque(maxlen=_STATS_WINDOW)
        self._occupancies: deque = deque(maxlen=_STATS_WINDOW)

        if autostart:
            self.start()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Start the tick worker (idempotent)."""
        with self._cond:
            if self._worker is not None and self._worker.is_alive():
                return
            self._closing = False
            self._worker = threading.Thread(
                target=self._run, name="solver-service-tick", daemon=True
            )
            self._worker.start()

    def close(self) -> None:
        """Stop admitting, drain the queue, join the worker."""
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join()
            self._worker = None

    def __enter__(self) -> "SolverService":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- admission -------------------------------------------------------

    def submit(
        self,
        dcop: Any = None,
        algo: Optional[str] = None,
        algo_params: Optional[Mapping[str, Any]] = None,
        *,
        rounds: int = 200,
        seed: int = 0,
        chunk_size: int = 64,
        convergence_chunks: int = 0,
        n_restarts: int = 1,
        timeout: Optional[float] = None,
        session: Optional[str] = None,
        set_values: Optional[Mapping[str, Any]] = None,
    ) -> PendingResult:
        """Admit one solve request; returns a :class:`PendingResult`.

        ``dcop`` is a DCOP object, a yaml file path, or yaml TEXT (any
        string containing a newline is treated as text — the wire
        protocol's form).  ``session`` names a session: its first
        request must carry the dcop and pins an incremental compiler;
        later requests may omit ``dcop`` and stream ``set_values``
        deltas ({external variable: value}) instead.  Validation
        errors raise HERE (before admission); dispatch errors surface
        from ``PendingResult.result()``.
        """
        with self._cond:
            if self._closing:
                raise ServiceError("service is closed")
        if n_restarts < 1:
            raise ValueError(
                f"n_restarts must be >= 1, got {n_restarts}"
            )
        if set_values is not None and session is None:
            raise ValueError(
                "set_values streams external-variable deltas into a "
                "pinned session — pass session=<name> (docs/serving.md)"
            )

        sess = self._sessions.get(session) if session else None
        if sess is not None:
            if dcop is not None:
                # a follow-up may resend the SAME dcop (a reconnecting
                # wire client naturally re-ships its yaml text); a
                # DIFFERENT one would silently solve the pinned
                # problem under the new problem's name — reject it
                _, key = self._load_dcop(dcop)
                if key != sess.dcop_key:
                    raise ServiceError(
                        f"session {session!r} is pinned to a "
                        "different dcop — close_session first, or "
                        "use a new session name (docs/serving.md)"
                    )
            dcop_obj, dcop_key = sess.dcop, sess.dcop_key
        else:
            if dcop is None:
                raise ValueError(
                    "dcop is required (only follow-up requests of an "
                    "open session may omit it)"
                )
            dcop_obj, dcop_key = self._load_dcop(dcop)
        if algo is None:
            raise ValueError("algo is required")

        from pydcop_tpu.algorithms import (
            load_algorithm_module,
            prepare_algo_params,
            resolve_algo,
        )

        algo_name, params_in = resolve_algo(algo, algo_params)
        module = load_algorithm_module(algo_name)
        params = prepare_algo_params(params_in, module.algo_params)

        req = _Request(
            dcop=dcop_obj, dcop_key=dcop_key, algo=algo_name,
            params=params, rounds=rounds, seed=seed,
            chunk_size=chunk_size,
            convergence_chunks=convergence_chunks,
            n_restarts=n_restarts, timeout=timeout, session=session,
            set_values=dict(set_values) if set_values else None,
            pending=PendingResult(),
        )
        met = get_metrics()
        if met.enabled:
            met.inc("service.requests")
        with self._cond:
            if self._closing:
                raise ServiceError("service is closed")
            req.enqueue_t = time.perf_counter()
            self._queue.append(req)
            self._cond.notify_all()
        with self._stats_lock:
            self._n_requests += 1
        return req.pending

    def solve(self, *args, **kwargs) -> Dict[str, Any]:
        """Blocking convenience: ``submit(...).result()``."""
        return self.submit(*args, **kwargs).result()

    def close_session(self, name: str) -> bool:
        """Drop a pinned session (frees its compiled state); returns
        whether it existed."""
        with self._cond:
            return self._sessions.pop(name, None) is not None

    # -- stats -----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Always-on serving aggregates: request/tick/dispatch counts,
        coalesce ratio, occupancy and queue-wait/latency percentiles
        over a bounded recent window."""
        with self._stats_lock:
            waits = list(self._queue_waits)
            lats = list(self._latencies)
            occs = [float(o) for o in self._occupancies]
            out = {
                "requests": self._n_requests,
                "ticks": self._n_ticks,
                "dispatches": self._n_dispatches,
                "coalesced_requests": self._n_coalesced,
                "pad_instances": self._n_pad_instances,
                "errors": self._n_errors,
                "sessions": len(self._sessions),
            }
        out["coalesce_ratio"] = (
            round(len(lats) and sum(occs) / max(1, len(occs)), 4)
            if occs
            else 0.0
        )
        out["queue_wait_s"] = {
            "p50": _percentile(waits, 50),
            "p99": _percentile(waits, 99),
            "max": max(waits) if waits else 0.0,
        }
        out["latency_s"] = {
            "p50": _percentile(lats, 50),
            "p99": _percentile(lats, 99),
            "max": max(lats) if lats else 0.0,
        }
        out["batch_occupancy"] = {
            "p50": _percentile(occs, 50),
            "max": max(occs) if occs else 0.0,
        }
        return out

    # -- dcop loading + compiled-problem cache ---------------------------

    def _load_dcop(self, dcop: Any) -> Tuple[Any, Tuple]:
        """Normalize a request's dcop to (DCOP object, cache key).

        yaml TEXT keys by content hash (repeat submissions of the same
        text share one compile), paths by (realpath, mtime, size),
        objects by identity (the cache entry pins the object, so the
        id can never be recycled under the key)."""
        from pydcop_tpu.dcop.dcop import DCOP

        if isinstance(dcop, DCOP):
            return dcop, ("obj", id(dcop))
        if isinstance(dcop, str) and "\n" in dcop:
            key = (
                "yaml",
                hashlib.sha256(dcop.encode("utf-8")).hexdigest(),
            )
            with self._cond:
                cached = self._compiled.get(key)
            if cached is not None:
                return cached[0], key
            from pydcop_tpu.dcop.yamldcop import load_dcop

            return load_dcop(dcop), key
        if isinstance(dcop, (str, list, tuple)):
            from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

            if isinstance(dcop, str):
                path = os.path.realpath(dcop)
                st = os.stat(path)
                key = ("path", path, st.st_mtime_ns, st.st_size)
                with self._cond:
                    cached = self._compiled.get(key)
                if cached is not None:
                    return cached[0], key
            else:
                key = ("paths", tuple(dcop))
            return load_dcop_from_file(dcop), key
        raise ValueError(
            f"dcop must be a DCOP object, a yaml path, or yaml text — "
            f"got {type(dcop).__name__}"
        )

    def _compiled_problem(self, req: _Request):
        """The request's CompiledProblem, from the LRU cache when the
        dcop identity was seen before (the host-side analogue of the
        runner cache: repeated requests skip the numpy re-tabulation,
        not just the XLA compile)."""
        key = req.dcop_key
        with self._cond:
            hit = self._compiled.get(key)
            if hit is not None and (
                key[0] != "obj" or hit[0] is req.dcop
            ):
                self._compiled.move_to_end(key)
                return hit[1]
        from pydcop_tpu.ops.compile import compile_dcop

        problem = compile_dcop(req.dcop, pad_policy=self.pad_policy)
        with self._cond:
            self._compiled[key] = (req.dcop, problem)
            while len(self._compiled) > self._compile_cache_max:
                self._compiled.popitem(last=False)
        return problem

    # -- the tick loop ---------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closing:
                    self._cond.wait()
                if not self._queue:
                    return  # closing, drained
                # tick policy: fire on max_batch pending, or when the
                # oldest request has waited max_wait
                while (
                    len(self._queue) < self.tick.max_batch
                    and not self._closing
                ):
                    left = self.tick.max_wait - (
                        time.perf_counter() - self._queue[0].enqueue_t
                    )
                    if left <= 0:
                        break
                    self._cond.wait(timeout=left)
                batch = [
                    self._queue.popleft()
                    for _ in range(
                        min(len(self._queue), self.tick.max_batch)
                    )
                ]
            try:
                self._dispatch_tick(batch)
            except Exception as e:  # noqa: BLE001 — the worker must
                # outlive ANY tick (an escaped telemetry/bookkeeping
                # error would otherwise kill the thread silently and
                # leave every future request queued forever): fail
                # the batch's undelivered requests, keep ticking
                try:
                    self._fail(batch, e)
                except Exception:  # noqa: BLE001 — even the failure
                    # path (tracer/metrics) can be what's broken;
                    # unblocking the clients is the one hard duty left
                    for req in batch:
                        if not req.pending.done():
                            req.pending._set_error(
                                ServiceError(
                                    f"tick dispatch failed: "
                                    f"{type(e).__name__}: {e}"
                                )
                            )

    def _dispatch_tick(self, batch: List[_Request]) -> None:
        from pydcop_tpu.engine.supervisor import supervision

        met = get_metrics()
        tr = get_tracer()
        tick_t = time.perf_counter()
        for req in batch:
            req.queue_wait = tick_t - req.enqueue_t
            if met.enabled:
                met.observe(
                    "service.queue_wait_s", req.queue_wait,
                    buckets=_LATENCY_BUCKETS,
                )
            if tr.enabled:
                tr.add_span(
                    "service.queue-wait", "service", req.enqueue_t,
                    req.queue_wait, algo=req.algo,
                )
        with self._stats_lock:
            self._n_ticks += 1
            self._queue_waits.extend(r.queue_wait for r in batch)
        if met.enabled:
            met.inc("service.ticks")
            met.gauge("service.queue_depth", len(self._queue))

        # session requests keep FIFO order per session; stateless
        # requests coalesce into groups
        with supervision(self._sup):
            stateless: List[_Request] = []
            for req in batch:
                if req.session is not None:
                    self._dispatch_session(req)
                else:
                    stateless.append(req)
            if stateless:
                self._dispatch_groups(stateless)

    # -- dispatch: coalesced stateless groups ----------------------------

    def _group_key(self, req: _Request) -> Tuple:
        from pydcop_tpu.engine.host_batch import statics_signature

        return (
            req.algo,
            statics_signature(req.params),
            req.rounds,
            req.chunk_size,
            req.convergence_chunks,
            req.n_restarts,
            # timeouts act GROUP-wide at chunk boundaries
            # (run_many_batched), so a request carrying one may only
            # coalesce with requests carrying the same one — a tight
            # deadline must never truncate a batchmate's solve
            req.timeout,
        )

    def _dispatch_groups(self, reqs: List[_Request]) -> None:
        partitions: "OrderedDict[Tuple, List[_Request]]" = OrderedDict()
        for req in reqs:
            partitions.setdefault(self._group_key(req), []).append(req)
        for part in partitions.values():
            from pydcop_tpu.algorithms import load_algorithm_module

            module = load_algorithm_module(part[0].algo)
            try:
                if hasattr(module, "solve_host"):
                    self._dispatch_host(part, module)
                else:
                    self._dispatch_device(part, module)
            except Exception as e:  # noqa: BLE001 — fail this
                # partition's requests, keep serving the others
                self._fail(part, e)

    def _finish(
        self, req: _Request, result: Dict[str, Any], group_n: int
    ) -> None:
        met = get_metrics()
        tr = get_tracer()
        latency = time.perf_counter() - req.enqueue_t
        result["queue_wait"] = req.queue_wait
        result["instances_batched"] = group_n
        result.pop("telemetry", None)  # service-level, not per-request
        if met.enabled:
            met.observe(
                "service.latency_s", latency, buckets=_LATENCY_BUCKETS
            )
            if group_n > 1:
                met.inc("service.coalesced")
        if tr.enabled:
            tr.add_span(
                "service.request", "service", req.enqueue_t, latency,
                algo=req.algo, instances=group_n, status=result.get("status"),
            )
        with self._stats_lock:
            self._latencies.append(latency)
            if group_n > 1:
                self._n_coalesced += 1
        req.pending._set_result(result)

    def _fail(self, reqs: List[_Request], error: BaseException) -> None:
        # a partition can span several stacked groups; groups that
        # already delivered must keep their results when a LATER
        # group's dispatch raises
        reqs = [r for r in reqs if not r.pending.done()]
        if not reqs:
            return
        met = get_metrics()
        tr = get_tracer()
        if tr.enabled:
            tr.event(
                "service-error", cat="service",
                error=f"{type(error).__name__}: {error}"[:300],
                requests=len(reqs),
            )
        if met.enabled:
            met.inc("service.errors", len(reqs))
        with self._stats_lock:
            self._n_errors += len(reqs)
        for req in reqs:
            req.pending._set_error(
                ServiceError(
                    f"dispatch failed for algo={req.algo!r}: "
                    f"{type(error).__name__}: {error}"
                )
            )

    def _record_dispatch(self, k: int, padded: int) -> None:
        met = get_metrics()
        if met.enabled:
            met.inc("service.dispatches")
            met.observe(
                "service.batch_occupancy", k,
                buckets=(1, 2, 4, 8, 16, 32, 64, 128),
            )
            if padded:
                met.inc("service.pad_instances", padded)
        with self._stats_lock:
            self._n_dispatches += 1
            self._occupancies.append(k)
            self._n_pad_instances += padded

    def _dispatch_device(self, part: List[_Request], module) -> None:
        from pydcop_tpu.api import _result_dict
        from pydcop_tpu.engine.batched import run_many_batched
        from pydcop_tpu.ops.compile import stack_problems

        tr = get_tracer()
        r0 = part[0]
        problems = [self._compiled_problem(r) for r in part]
        for stacked in stack_problems(problems):
            group = [part[i] for i in stacked.indices]
            k = len(group)
            # occupancy bucketing: pad the group to a pow-2 instance
            # count by repeating the last member so the vmapped runner
            # cache (keyed on K) converges on log2 executables instead
            # of one per distinct tick size; pad lanes re-solve a real
            # instance and are discarded below
            padded = 0
            if self.instance_bucket == "pow2" and k > 1:
                k_pad = _next_pow2(k)
                if k_pad != k:
                    padded = k_pad - k
                    stacked = stack_problems(
                        stacked.host_problems
                        + [stacked.host_problems[-1]] * padded
                    )[0]
            # the group key pins one shared timeout per partition
            run_timeout = None
            if r0.timeout is not None:
                run_timeout = max(
                    r0.timeout
                    - (time.perf_counter() - r0.enqueue_t),
                    0.01,
                )
            self._record_dispatch(k, padded)
            params_list = [g.params for g in group]
            seeds = [g.seed for g in group]
            if padded:
                params_list = params_list + [params_list[-1]] * padded
                seeds = seeds + [seeds[-1]] * padded
            with tr.span(
                "service.dispatch", cat="service", instances=k,
                padded=padded, algo=r0.algo,
            ):
                results = run_many_batched(
                    stacked,
                    module,
                    params_list,
                    rounds=r0.rounds,
                    seeds=seeds,
                    timeout=run_timeout,
                    chunk_size=r0.chunk_size,
                    convergence_chunks=r0.convergence_chunks,
                    n_restarts=r0.n_restarts,
                )
            for req, rr in zip(group, results):  # pads fall off zip
                out = _result_dict(rr)
                out["time"] = rr.time / k
                self._finish(req, out, k)

    def _dispatch_host(self, part: List[_Request], module) -> None:
        """Exact host-path algorithms (DPOP, SyncBB): one
        ``run_many_host`` call per partition — DPOP requests merge
        their UTIL sweeps exactly as ``api.solve_many`` merges them."""
        from pydcop_tpu.engine.host_batch import run_many_host

        tr = get_tracer()
        r0 = part[0]
        k = len(part)
        # the group key pins one shared timeout per partition
        run_timeout = None
        if r0.timeout is not None:
            run_timeout = max(
                r0.timeout - (time.perf_counter() - r0.enqueue_t),
                0.01,
            )
        self._record_dispatch(k, 0)
        with tr.span(
            "service.dispatch", cat="service", instances=k,
            padded=0, algo=r0.algo,
        ):
            results = run_many_host(
                [g.dcop for g in part],
                module,
                [g.params for g in part],
                timeout=run_timeout,
                pad_policy=self.pad_policy,
            )
        for req, out in zip(part, results):
            self._finish(req, out, out.get("instances_batched", k))

    # -- dispatch: session-affine requests -------------------------------

    def _dispatch_session(self, req: _Request) -> None:
        try:
            result = self._solve_session(req)
        except Exception as e:  # noqa: BLE001 — per-request failure
            self._fail([req], e)
            return
        self._finish(req, result, 1)

    def _solve_session(self, req: _Request) -> Dict[str, Any]:
        from pydcop_tpu.api import _result_dict
        from pydcop_tpu.engine.batched import run_batched

        tr = get_tracer()
        sess = self._sessions.get(req.session)
        if sess is None:
            from pydcop_tpu.engine.incremental import (
                IncrementalCompiler,
            )

            sess = _Session(
                IncrementalCompiler(
                    req.dcop, pad_policy=self.pad_policy
                ),
                req.dcop,
                req.dcop_key,
            )
            self._sessions[req.session] = sess
            met = get_metrics()
            if met.enabled:
                met.inc("service.sessions_opened")
        if req.set_values:
            unknown = set(req.set_values) - set(
                sess.dcop.external_variables
            )
            if unknown:
                raise ServiceError(
                    f"set_values names {sorted(unknown)}, not external "
                    "variables of the session's dcop — session deltas "
                    "update externals only (structure changes need a "
                    "new session, docs/serving.md)"
                )
            sess.ext_values.update(req.set_values)
        problem, _fp = sess.compiler.compile({}, sess.ext_values)
        if problem is None:
            raise ServiceError(
                "session dcop has no live variables to solve"
            )
        sess.segments += 1
        run_timeout = None
        if req.timeout is not None:
            run_timeout = max(
                req.timeout - (time.perf_counter() - req.enqueue_t),
                0.01,
            )
        self._record_dispatch(1, 0)
        with tr.span(
            "service.dispatch", cat="service", instances=1, padded=0,
            algo=req.algo, session=req.session,
            segment=sess.segments,
        ):
            result = run_batched(
                problem,
                _load_module(req.algo),
                req.params,
                rounds=req.rounds,
                seed=req.seed,
                timeout=run_timeout,
                chunk_size=req.chunk_size,
                convergence_chunks=req.convergence_chunks,
                n_restarts=req.n_restarts,
            )
        out = _result_dict(result)
        out["session"] = req.session
        out["segment"] = sess.segments
        return out


def _load_module(algo_name: str):
    from pydcop_tpu.algorithms import load_algorithm_module

    return load_algorithm_module(algo_name)


# ---------------------------------------------------------------------------
# wire protocol: newline-JSON frames (the hostnet control-plane framing)
# ---------------------------------------------------------------------------
#
# request:  {"op": "solve", "id": N, "algo": ..., "dcop": yaml-text |
#            path, "params": {...}, "rounds": ..., "seed": ...,
#            "session": ..., "set_values": {...}, ...}
#           {"op": "stats" | "ping" | "close_session" | "shutdown",
#            "id": N, ...}
# response: {"id": N, "ok": true, "result"|"stats"|...: ...}
#           {"id": N, "ok": false, "error": "..."}
#
# One request in flight per connection (a client wanting concurrency
# opens more connections — that is exactly what makes requests
# coalesce); responses carry the request id regardless.

_SOLVE_FIELDS = (
    "rounds", "seed", "chunk_size", "convergence_chunks",
    "n_restarts", "timeout", "session", "set_values",
)

#: results are trimmed for the wire: the per-round cost trace can be
#: orders of magnitude bigger than the answer
_WIRE_DROP = ("cost_trace", "restart_costs")


class ServiceServer:
    """TCP front for a :class:`SolverService`: accepts connections,
    one handler thread per connection, newline-JSON frames."""

    def __init__(
        self,
        service: SolverService,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.service = service
        self._server = socket.create_server((host, port))
        self.address: Tuple[str, int] = (
            host, self._server.getsockname()[1]
        )
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._lock = threading.Lock()
        self._accept = threading.Thread(
            target=self._accept_loop, name="solver-service-accept",
            daemon=True,
        )
        self._accept.start()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until :meth:`close` / a ``shutdown`` op (or the
        timeout); returns True when shut down."""
        return self._shutdown.wait(timeout)

    def close(self) -> None:
        self._shutdown.set()
        try:
            self._server.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
            threads = list(self._threads)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in threads:
            t.join(timeout=5)

    def __enter__(self) -> "ServiceServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _addr = self._server.accept()
            except OSError:
                return  # closed
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(
                target=self._handle, args=(conn,),
                name="solver-service-conn", daemon=True,
            )
            with self._lock:
                self._threads.append(t)
            t.start()

    def _handle(self, conn: socket.socket) -> None:
        from pydcop_tpu.infrastructure.hostnet import _recv, _send

        reader = conn.makefile("rb")
        try:
            while not self._shutdown.is_set():
                try:
                    msg = _recv(reader)
                except (OSError, ValueError):
                    return
                if msg is None:
                    return
                rid = msg.get("id")
                try:
                    reply = self._serve_op(msg)
                except Exception as e:  # noqa: BLE001 — per-request
                    reply = {
                        "ok": False,
                        "error": f"{type(e).__name__}: {e}",
                    }
                reply["id"] = rid
                try:
                    _send(conn, reply)
                except OSError:
                    return
                if msg.get("op") == "shutdown":
                    self._shutdown.set()
                    return
        finally:
            try:
                reader.close()
                conn.close()
            except OSError:
                pass
            # "concurrency is connections" means a resident server
            # sees millions of short-lived ones: drop this handler's
            # bookkeeping or _conns/_threads grow without bound
            with self._lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass
                try:
                    self._threads.remove(threading.current_thread())
                except ValueError:
                    pass

    def _serve_op(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        op = msg.get("op")
        if op == "ping":
            return {"ok": True, "pong": True}
        if op == "stats":
            return {"ok": True, "stats": self.service.stats()}
        if op == "close_session":
            return {
                "ok": True,
                "closed": self.service.close_session(
                    msg.get("session", "")
                ),
            }
        if op == "shutdown":
            return {"ok": True, "stopping": True}
        if op == "solve":
            kwargs = {
                k: msg[k] for k in _SOLVE_FIELDS if msg.get(k) is not None
            }
            result = self.service.solve(
                msg.get("dcop"),
                msg.get("algo"),
                msg.get("params") or None,
                **kwargs,
            )
            result = {
                k: v for k, v in result.items() if k not in _WIRE_DROP
            }
            return {"ok": True, "result": result}
        raise ServiceError(f"unknown op {op!r}")


class ServiceClient:
    """Thin blocking client for a :class:`ServiceServer` (also
    exported as ``pydcop_tpu.api.ServiceClient``).

    One request in flight at a time per client; open more clients for
    concurrency — concurrent clients are exactly what the service
    coalesces.  ``dcop`` arguments that name an existing file are
    read and shipped as yaml text, so the server needs no shared
    filesystem.
    """

    def __init__(
        self,
        address: Union[str, Tuple[str, int]],
        timeout: Optional[float] = None,
    ):
        if isinstance(address, str):
            host, _, port = address.rpartition(":")
            address = (host or "127.0.0.1", int(port))
        self._sock = socket.create_connection(
            address, timeout=timeout
        )
        self._reader = self._sock.makefile("rb")
        self._lock = threading.Lock()
        self._next_id = 0

    def _call(self, op: str, **fields) -> Dict[str, Any]:
        from pydcop_tpu.infrastructure.hostnet import _recv, _send

        with self._lock:
            self._next_id += 1
            rid = self._next_id
            _send(self._sock, {"op": op, "id": rid, **fields})
            while True:
                reply = _recv(self._reader)
                if reply is None:
                    raise ServiceError(
                        "service connection closed mid-request"
                    )
                if reply.get("id") == rid:
                    break
        if not reply.get("ok"):
            raise ServiceError(
                reply.get("error", "service request failed")
            )
        return reply

    def solve(
        self,
        dcop: Optional[str] = None,
        algo: Optional[str] = None,
        params: Optional[Mapping[str, Any]] = None,
        **kwargs,
    ) -> Dict[str, Any]:
        """Solve over the wire; kwargs mirror
        :meth:`SolverService.submit` (rounds, seed, chunk_size,
        convergence_chunks, n_restarts, timeout, session,
        set_values)."""
        unknown = set(kwargs) - set(_SOLVE_FIELDS)
        if unknown:
            raise ValueError(
                f"unknown solve field(s) {sorted(unknown)}; the wire "
                f"protocol accepts {_SOLVE_FIELDS}"
            )
        if (
            isinstance(dcop, str)
            and "\n" not in dcop
            and os.path.isfile(dcop)
        ):
            with open(dcop, encoding="utf-8") as f:
                dcop = f.read()
        reply = self._call(
            "solve", dcop=dcop, algo=algo,
            params=dict(params) if params else None, **kwargs,
        )
        return reply["result"]

    def stats(self) -> Dict[str, Any]:
        return self._call("stats")["stats"]

    def ping(self) -> bool:
        return bool(self._call("ping").get("pong"))

    def close_session(self, name: str) -> bool:
        return bool(
            self._call("close_session", session=name).get("closed")
        )

    def shutdown(self) -> None:
        """Ask the server process to stop serving."""
        self._call("shutdown")

    def close(self) -> None:
        try:
            self._reader.close()
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ServiceClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
