"""Continuous-batching solver service: the resident process that turns
one-shot solve calls into a serving loop (ROADMAP item 4, the
"millions of users" path).

``api.solve_many`` (PR 4/5) batches instances *within one call*; this
module batches *across concurrent callers*, the way LLM serving does:

- an **admission queue** collects requests from any number of client
  threads / connections;
- a **tick policy** (:class:`TickPolicy`) bounds latency: a tick fires
  as soon as ``max_batch`` requests are pending OR the oldest request
  has waited ``max_wait`` seconds — so a lone request never waits more
  than one ``max_wait`` and a burst rides the vmap;
- each tick **coalesces** its requests into
  :func:`~pydcop_tpu.ops.compile.problem_group_key` buckets (after the
  same static-params partition ``api.solve_many`` uses) and dispatches
  every group as ONE ``run_many_batched`` device program — requests
  that share a bucket are the same executable with different data, so
  coalescing them is a memcpy-stack plus one warm dispatch;
- **occupancy bucketing** pads each group to a power-of-two instance
  count by repeating its last member (results discarded), so the
  vmapped runner cache — which keys on K — converges on a handful of
  executables and steady-state ticks perform ZERO XLA compiles no
  matter how ragged the traffic is
  (``tools/recompile_guard.py:run_service_guard`` pins this);
- **warm state is the point**: the chunk-runner cache
  (``engine/batched.py``), the compiled-problem cache (keyed on the
  request's dcop identity), and per-session
  :class:`~pydcop_tpu.engine.incremental.IncrementalCompiler` pins all
  persist across requests, so after the cold tick a request costs
  dispatch + memcpy, never tracing or XLA;
- **session affinity**: a client that names a ``session`` gets its
  problem pinned to an IncrementalCompiler; streaming ``set_values``
  deltas (external-variable updates) re-tabulates only the touched
  constraints on device (``compile.incremental``) — zero full
  recompiles after the first segment;
- every dispatch runs under the service's
  :class:`~pydcop_tpu.engine.supervisor.Supervisor` (PR 6), so a
  poisoned or OOM-ing request quarantines / splits instead of failing
  its batchmates, and the device-layer chaos kinds (``device_oom``,
  ``device_transient``, ``nan_inject``) exercise exactly those paths
  against a live service.

Coalesced results are bit-identical to per-request sequential
``api.solve`` calls with the same ``pad_policy`` — the per-instance
RNG-parity contract of ``run_many_batched`` (``docs/performance.md``)
— and a request that shares a tick with a poisoned batchmate still
returns the exact fault-free answer.

Wire protocol (:class:`ServiceServer` / :class:`ServiceClient`):
newline-JSON frames over TCP, the same framing as the hostnet control
plane (``infrastructure/hostnet.py``).  One request in flight per
connection; concurrency is connections — N clients on N sockets
coalesce into shared ticks.  ``pydcop_tpu serve`` is the CLI front
(``docs/serving.md`` covers the tick policy, affinity, and failure
semantics under the PR 6 recovery matrix).

Production hardening (the serving loop is the fourth supervised layer
of the recovery matrix, ``docs/faults.md``):

- **overload control** — the admission queue is bounded
  (``max_queue``); requests past the bound, and requests whose
  ``timeout`` the service already knows it cannot meet at the current
  queue depth (predicted wait from a median of recent tick durations),
  are
  rejected at admission with ``status="shed"`` in microseconds
  instead of burning a dispatch slot on a doomed solve.  The wire
  server adds a per-connection in-flight cap as backpressure.
- **graceful lifecycle** — :meth:`SolverService.close` drains: new
  admissions are refused, queued ticks finish and deliver, and the
  final **session checkpoint** (each pinned session's dcop identity,
  its ordered applied ``set_values`` deltas, and counters) is written
  atomically.  A restarted service (``serve --resume``) replays the
  deltas through the ``IncrementalCompiler`` in order — the exact
  update arithmetic of the original — so a reconnecting session's
  follow-up is ``compile.incremental``-only and bit-identical to an
  undisturbed service.
- **wire-level chaos + idempotent retries** — the seeded FaultPlan
  wire kinds (``conn_drop``, ``slow_client``, ``frame_corrupt``)
  inject in :class:`ServiceServer`'s reply path;
  :class:`ServiceClient` reconnects with keyed-backoff
  (``utils/backoff.py``) and resends under a per-request idempotency
  key, which the server answers from a bounded reply cache — a
  dropped-but-computed response is replayed, never re-solved.
  Malformed or oversized frames get a structured error reply and the
  connection survives.

Telemetry (``docs/observability.md``): counters ``service.requests``/
``service.ticks``/``service.dispatches``/``service.coalesced``/
``service.pad_instances``/``service.shed``/
``service.frames_rejected``/``service.sessions_restored``/
``service.replayed_replies``/``service.client_retries``, histograms
``service.queue_wait_s``/``service.latency_s``/
``service.batch_occupancy``/``service.shed_latency_s``, and
per-request ``service.queue-wait`` + ``service.request`` spans /
per-group ``service.dispatch`` spans / a final ``service.drain`` span
that ``pydcop_tpu trace-summary`` folds into queue-wait / occupancy /
latency percentiles plus shed/retry/drain rows.

Serving observability (ISSUE 14, ``docs/observability.md`` "Serving
observability"): every request carries a TRACE CONTEXT — the wire
client mints a trace id (stable across idempotent resends) + a
per-attempt span id (``telemetry/context.py``) and the service tags
its spans, the group dispatch, and every supervisor event inside it
with the id(s); in-process submits get a service-minted id
deterministic in admission order.  Every reply returns a per-request
PHASE BREAKDOWN (``result["phases"]``: admission / queue / compile /
device / decode / reply_write — contiguous segments whose sum is the
server-side share of the client latency), and ``trace-summary
--requests`` stitches client + server trace files into one correlated
timeline per request.  On a shed / quarantine / dispatch-error /
drain trigger the session's always-on flight-recorder ring is dumped
(``flight_dump=``/``serve --flight_dump``), the triggering request's
trace id front and center; ``serve --metrics_port`` exposes the live
registry as ``/metrics`` (Prometheus text) + ``/healthz``
(:meth:`SolverService.health` — flips to ``draining`` during a
graceful shutdown).

This module is import-light by design: jax (and the batched engine)
load on first dispatch, not at import, so ``api.ServiceClient`` stays
usable from jax-free client processes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import socket
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Mapping, Optional, Tuple, Union

from pydcop_tpu.telemetry import (
    get_flight_recorder,
    get_metrics,
    get_tracer,
)
from pydcop_tpu.telemetry.context import (
    attempt_span_id,
    mint_trace_id,
    parse_wire_trace,
    trace_scope,
    wire_trace,
)
from pydcop_tpu.telemetry.summary import _percentile

#: queue-wait / latency histogram buckets (seconds) — service
#: latencies live in the 1ms..10s band, below the metrics module's
#: generic defaults
_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)

#: bounded stats windows (per-service): enough for stable p99 at the
#: bench's request counts without growing forever in a resident process
_STATS_WINDOW = 8192

#: per-session delta-log bound: a resident session streaming
#: set_values forever must not grow its checkpoint (and the resume
#: replay it implies) with session AGE — past the bound the oldest
#: half folds into one cumulative delta (see _Session.record_delta)
_DELTA_LOG_MAX = 4096


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _pad_policy_doc(pad_policy: Any) -> Dict[str, Any]:
    """The canonical JSON form of a pad policy (string spec or
    PadPolicy object) — what session checkpoints store and compare."""
    from pydcop_tpu.ops.padding import as_pad_policy

    return dataclasses.asdict(as_pad_policy(pad_policy))


def _exact_doc(sess: "_Session") -> Dict[str, Any]:
    """The checkpointable exact-session record: {algo: params} for
    every MEMOIZED exact session (engine/memo.py) the session holds —
    what the restore/replication replay re-warms.  Plain pinned
    clones (syncbb) carry no memo worth warming and are rebuilt
    lazily instead."""
    return {
        a: dict(p)
        for a, p in sess.exact_params.items()
        if getattr(sess.exact.get(a), "memo", None) is not None
    }


def _dcop_source(dcop: Any) -> Optional[Tuple[str, str]]:
    """The serializable identity of a request's dcop, for session
    checkpoints: yaml text ships verbatim, paths by realpath; DCOP
    objects have no wire identity (None — serialized at checkpoint
    time via ``dcop_yaml`` when possible)."""
    if isinstance(dcop, str) and "\n" in dcop:
        return ("yaml", dcop)
    if isinstance(dcop, str):
        return ("path", os.path.realpath(dcop))
    return None


@dataclasses.dataclass
class TickPolicy:
    """When a tick fires: as soon as ``max_batch`` requests are
    pending, or as soon as the OLDEST pending request has waited
    ``max_wait`` seconds — whichever comes first.  ``max_batch`` also
    caps how many requests one tick drains (a burst beyond it rolls
    into the immediately-following tick), so dispatch width — and with
    it HBM footprint and per-tick latency — stays bounded."""

    max_batch: int = 32
    max_wait: float = 0.01

    def __post_init__(self) -> None:
        if self.max_batch < 1:
            raise ValueError(
                f"max_batch must be >= 1, got {self.max_batch}"
            )
        if self.max_wait < 0:
            raise ValueError(
                f"max_wait must be >= 0, got {self.max_wait}"
            )


class PendingResult:
    """Handle for a submitted request: :meth:`result` blocks until the
    service tick that carried the request completes (or raises what
    the dispatch raised)."""

    def __init__(self) -> None:
        self._done = threading.Event()
        self._result: Optional[Dict[str, Any]] = None
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()
        self._callbacks: List[Any] = []

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                "service request still pending after "
                f"{timeout}s (is the service running?)"
            )
        if self._error is not None:
            raise self._error
        assert self._result is not None
        return self._result

    def add_done_callback(self, fn) -> None:
        """Run ``fn(self)`` once the request reaches its terminal
        state (immediately if it already has).  The wire server uses
        this to pipeline replies instead of parking one blocked thread
        per in-flight request."""
        with self._lock:
            if not self._done.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    # -- service side ----------------------------------------------------

    def _finish(self) -> None:
        with self._lock:
            self._done.set()
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            try:
                fn(self)
            except Exception:  # noqa: BLE001 — a broken callback must
                # not take down the tick worker delivering the result
                pass

    def _set_result(self, result: Dict[str, Any]) -> None:
        self._result = result
        self._finish()

    def _set_error(self, error: BaseException) -> None:
        self._error = error
        self._finish()


@dataclasses.dataclass
class _Request:
    """One admitted solve or infer request (internal)."""

    dcop: Any  # DCOP object (loaded at admission)
    dcop_key: Tuple  # compiled-problem cache key
    algo: str
    params: Dict[str, Any]  # prepared algo params
    rounds: int
    seed: int
    chunk_size: int
    convergence_chunks: int
    n_restarts: int
    timeout: Optional[float]
    session: Optional[str]
    set_values: Optional[Dict[str, Any]]
    pending: PendingResult
    # serializable dcop identity for session checkpoints:
    # ("yaml", text) / ("path", realpath) / None for in-process objects
    dcop_src: Optional[Tuple[str, str]] = None
    enqueue_t: float = 0.0
    queue_wait: float = 0.0
    # inference requests (submit_infer): the query string and its
    # knobs — the QUERY joins the dispatch partition key, so mixed
    # kbest/map/log_z traffic in one tick coalesces per query and
    # never mixes sweeps across queries
    query: Optional[str] = None
    infer_kw: Optional[Dict[str, Any]] = None
    # request trace context (telemetry/context.py): the wire client's
    # trace id + per-attempt span id, or a service-minted id for
    # in-process submits — what correlates this request's spans
    # across processes and client retries
    trace_id: Optional[str] = None
    trace_span: Optional[str] = None
    trace_attempt: int = 1
    # phase-breakdown timestamps (docs/observability.md, "Serving
    # observability"): contiguous segments from submit entry to
    # result delivery, attached to the reply as result["phases"]
    t_sub: float = 0.0  # submit() entry
    admit_s: float = 0.0  # submit entry -> enqueued
    dispatch_t: float = 0.0  # this request's group started processing
    compile_s: float = 0.0  # problem compile + stack/pad, pre-device
    device_s: float = 0.0  # the device (or host-solve) run
    decode_t0: float = 0.0  # run done; decode runs until _finish


class _Session:
    """One client's pinned incremental-compile state: the
    :class:`~pydcop_tpu.engine.incremental.IncrementalCompiler` built
    on the session's FIRST request plus the accumulated external
    values its ``set_values`` deltas stream in.  Segment 2+ costs a
    device delta-update (``compile.incremental``) or nothing
    (``compile.reused``) — never a host rebuild or an XLA compile."""

    def __init__(
        self,
        compiler,
        dcop,
        dcop_key: Tuple,
        source: Optional[Tuple[str, str]] = None,
    ) -> None:
        self.compiler = compiler
        self.dcop = dcop
        self.dcop_key = dcop_key  # admission identity of segment 1
        self.source = source  # checkpointable identity (yaml/path)
        self.ext_values: Dict[str, Any] = {}
        # the ordered applied set_values deltas — a restarted service
        # replays them through the IncrementalCompiler so a follow-up
        # lands on bit-identical device tables (docs/serving.md)
        self.deltas: List[Dict[str, Any]] = []
        self.segments = 0
        # exact-algorithm state: per-algo pinned exact sessions —
        # dpop gets the memoized contraction session (engine/memo.py,
        # the O(delta) re-solve path), other solve_host algos a plain
        # pinned clone — plus the JSON-safe params of each, so the
        # checkpoint/replication replay can re-warm the memo
        self.exact: Dict[str, Any] = {}
        self.exact_params: Dict[str, Dict[str, Any]] = {}

    def record_delta(self, delta: Dict[str, Any]) -> None:
        """Append one applied delta, keeping the log bounded: past
        ``_DELTA_LOG_MAX`` the oldest half folds into one cumulative
        delta.  Folding keeps the replay VALUE-equal (every touched
        constraint re-tabulates from the same final external state);
        only the f32 fold ORDER of unary-folded rows can drift by an
        ulp for sessions older than the bound — the price of a
        checkpoint and resume that are O(bound), not O(session age)."""
        self.deltas.append(dict(delta))
        if len(self.deltas) > _DELTA_LOG_MAX:
            half = len(self.deltas) // 2
            merged: Dict[str, Any] = {}
            for d in self.deltas[:half]:
                merged.update(d)
            self.deltas = [merged] + self.deltas[half:]


class ServiceError(RuntimeError):
    """A request the service could not solve (bad algo/params/dcop, or
    an unrecoverable dispatch failure); the message is the client-side
    report."""


class ServiceTransportError(ServiceError):
    """The request could not be exchanged with the service at all
    (connect/send/receive failed past the retry window) — as opposed
    to a structured server-side refusal.  Best-effort operations
    (:meth:`ServiceClient.shutdown`) tolerate this after the request
    was plausibly delivered."""


class SolverService:
    """The resident continuous-batching solver (module docstring).

    In-process use::

        with session() as tel, SolverService(pad_policy="pow2") as svc:
            pendings = [svc.submit(d, "dsa", {}) for d in dcops]
            results = [p.result() for p in pendings]

    ``submit`` is thread-safe: N client threads submitting
    concurrently coalesce into shared ticks.  :class:`ServiceServer`
    puts the same object behind a TCP socket for out-of-process
    clients (:class:`ServiceClient`).

    The service does not open a telemetry session of its own —
    counters/spans land in whatever session is active (the ``serve``
    command opens one for the server's lifetime; in-process embedders
    wrap the service in ``telemetry.session()``), and the always-on
    :meth:`stats` aggregates stay available without one.
    """

    def __init__(
        self,
        pad_policy: str = "pow2",
        tick: Optional[TickPolicy] = None,
        *,
        max_batch: Optional[int] = None,
        max_wait: Optional[float] = None,
        instance_bucket: str = "pow2",
        chaos: Optional[str] = None,
        chaos_seed: int = 0,
        retry_budget: Optional[int] = None,
        chunk_floor: Optional[int] = None,
        on_numeric_fault: Optional[str] = None,
        compile_cache_max: int = 256,
        max_queue: int = 1024,
        session_memo_bytes: int = 64 << 20,
        session_checkpoint: Optional[str] = None,
        resume: bool = False,
        flight_dump: Optional[str] = None,
        standbys: Optional[Sequence[str]] = None,
        autostart: bool = True,
    ):
        from pydcop_tpu.ops.padding import as_pad_policy

        as_pad_policy(pad_policy)  # fail fast on a malformed spec
        self.pad_policy = pad_policy
        if tick is None:
            tick = TickPolicy()
        if max_batch is not None:
            tick = dataclasses.replace(tick, max_batch=max_batch)
        if max_wait is not None:
            tick = dataclasses.replace(tick, max_wait=max_wait)
        self.tick = tick
        if instance_bucket not in ("pow2", "none"):
            raise ValueError(
                "instance_bucket must be 'pow2' or 'none', got "
                f"{instance_bucket!r}"
            )
        self.instance_bucket = instance_bucket

        if max_queue < 1:
            raise ValueError(
                f"max_queue must be >= 1, got {max_queue}"
            )
        self.max_queue = max_queue
        # per-session byte bound of the subtree-fingerprint message
        # memo (engine/memo.py) behind exact-algorithm session
        # follow-ups; <= 0 disables memoization (every follow-up
        # re-contracts the full tree)
        self.session_memo_bytes = int(session_memo_bytes)
        self.session_checkpoint = session_checkpoint
        # flight-recorder dump target: on a shed / quarantine /
        # dispatch-error / drain trigger the session's always-on ring
        # (telemetry/flightrec.py) is dumped here atomically, the
        # triggering request's trace id front and center.  Dumps are
        # throttled (below) — a sustained overload shedding hundreds
        # of requests/sec must not amplify itself into hundreds of
        # full-ring serializations/sec of the same overwritten file
        self.flight_dump = flight_dump
        self._flight_last = 0.0
        # per-service request ordinal: mints DETERMINISTIC trace ids
        # (pure in admission order) for in-process submits that carry
        # no wire trace context
        self._trace_ordinal = 0

        plan = None
        if chaos:
            from pydcop_tpu.faults import FaultPlan

            plan = FaultPlan.from_spec(chaos, chaos_seed)
            if plan.message_faults_configured or plan.crashes:
                raise ValueError(
                    "the solver service dispatches on the batched "
                    "engine, which has no message plane — chaos "
                    "accepts the DEVICE-layer kinds (device_oom, "
                    "device_transient, nan_inject) and the WIRE "
                    "kinds (conn_drop, slow_client, frame_corrupt) "
                    "only (docs/faults.md)"
                )
            if plan.fleet_faults_configured:
                raise ValueError(
                    "fleet-level chaos kinds (replica_kill) act on "
                    "a replicated serving fleet's processes — one "
                    "service cannot kill a replica of itself; use "
                    "`pydcop_tpu fleet --chaos` (docs/faults.md)"
                )
        self.chaos_plan = plan
        from pydcop_tpu.engine.supervisor import make_supervisor

        self._sup = make_supervisor(
            retry_budget=retry_budget, chunk_floor=chunk_floor,
            on_numeric_fault=on_numeric_fault, plan=plan,
        )

        self._cond = threading.Condition()
        self._close_lock = threading.Lock()  # serializes close()
        self._queue: deque = deque()
        self._closing = False
        self._worker: Optional[threading.Thread] = None
        self._sessions: Dict[str, _Session] = {}
        # compiled-problem cache: dcop identity -> CompiledProblem
        # (LRU; the value also pins the DCOP object so an id-keyed
        # entry can never alias a new object at a recycled address)
        self._compiled: "OrderedDict[Tuple, Tuple[Any, Any]]" = (
            OrderedDict()
        )
        self._compile_cache_max = compile_cache_max

        # always-on aggregates (stats()); bounded windows
        self._stats_lock = threading.Lock()
        self._n_requests = 0
        self._n_ticks = 0
        self._n_dispatches = 0
        self._n_coalesced = 0  # requests that shared a group with >= 1 other
        self._n_pad_instances = 0
        self._n_errors = 0
        self._n_shed = 0
        self._n_frames_rejected = 0
        self._n_sessions_restored = 0
        self._n_replayed_replies = 0
        self._queue_waits: deque = deque(maxlen=_STATS_WINDOW)
        self._latencies: deque = deque(maxlen=_STATS_WINDOW)
        self._occupancies: deque = deque(maxlen=_STATS_WINDOW)
        self._shed_lats: deque = deque(maxlen=_STATS_WINDOW)
        # the deadline-aware shed's capacity estimate: the MEDIAN of
        # a recent-tick-duration window (robust — one compile-heavy
        # cold tick must not poison the predictor into shedding
        # easily-meetable deadlines for the next N ticks, which a
        # decay-based EWMA would); None until the first tick lands
        # (the service never sheds on a deadline it has no data to
        # judge)
        self._tick_durs: deque = deque(maxlen=32)
        self._tick_med: Optional[float] = None
        self._drained = False

        # fleet replication (docs/serving.md, "The fleet"): the
        # standby addresses this replica streams its session delta
        # logs to, the persistent clients it streams over, and the
        # REPLICATED copies of OTHER replicas' sessions it holds as a
        # standby (promoted into _sessions on the first failed-over
        # frame).  One lock serializes standby mutation AND the
        # per-entry sends, so a standby always applies a session's
        # entries in segment order.
        self._repl_lock = threading.Lock()
        self._standby_addrs: List[str] = []
        self._repl_clients: Dict[str, "ServiceClient"] = {}
        self._standby_sessions: Dict[str, _Session] = {}
        self._n_replica_updates = 0
        self._n_replicated_segments = 0
        self._n_replication_errors = 0
        self._n_sessions_promoted = 0

        if resume:
            if not session_checkpoint:
                raise ValueError(
                    "resume=True needs session_checkpoint=<path> — "
                    "there is nothing else to resume from"
                )
            # no existence pre-check: resuming from a checkpoint that
            # is missing, truncated, or schema-drifted must FAIL with
            # a structured error, not silently start empty — a fleet
            # health watcher treats the dead process as unhealthy and
            # routes around it, whereas a silently-empty replica
            # would claim its ring arc with every session lost
            self.restore_sessions(session_checkpoint)

        if standbys:
            self.set_standbys(standbys)

        if autostart:
            self.start()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Start the tick worker (idempotent)."""
        with self._cond:
            if self._worker is not None and self._worker.is_alive():
                return
            self._closing = False
            self._drained = False
            self._worker = threading.Thread(
                target=self._run, name="solver-service-tick", daemon=True
            )
            self._worker.start()

    def close(self) -> None:
        """Graceful drain: stop admitting, finish every queued tick,
        join the worker, write the final session checkpoint (when
        ``session_checkpoint`` is configured) and flush the final
        queue-depth gauge.  Idempotent and safe under concurrent
        callers (a signal-path close racing a ``with``-block exit):
        the close lock serializes the drain, and late callers return
        once the first finished."""
        with self._close_lock:
            self._close_locked()

    def _close_locked(self) -> None:
        with self._cond:
            if self._drained:
                return
            self._closing = True
            self._cond.notify_all()
        t0 = time.perf_counter()
        if self._worker is not None:
            self._worker.join()
            self._worker = None
        if self.session_checkpoint:
            try:
                self.write_session_checkpoint(self.session_checkpoint)
            except Exception as e:  # noqa: BLE001 — a checkpoint
                # failure must not mask the drain (the in-flight
                # results were already delivered); record it where
                # the operator actually looks, not only on a trace
                # that may not be running
                print(
                    "service: session checkpoint write failed: "
                    f"{type(e).__name__}: {e}",
                    file=sys.stderr,
                )
                tr = get_tracer()
                if tr.enabled:
                    tr.event(
                        "service-checkpoint-error", cat="service",
                        error=f"{type(e).__name__}: {e}"[:300],
                    )
        with self._repl_lock:
            repl = list(self._repl_clients.values())
            self._repl_clients = {}
        for cli in repl:
            cli.close()
        self._drained = True
        met = get_metrics()
        if met.enabled:
            met.gauge("service.queue_depth", 0)
        tr = get_tracer()
        if tr.enabled:
            tr.add_span(
                "service.drain", "service", t0,
                time.perf_counter() - t0,
                sessions=len(self._sessions),
            )
        # the drain itself is a flight trigger: the last thing a
        # terminating service leaves behind is its recent timeline
        self._flight_trigger("drain", None)

    #: minimum seconds between non-drain flight dumps: the FIRST
    #: trigger of a failure episode captures the interesting window;
    #: later triggers inside the interval would serialize the same
    #: ~4096-record ring again only to overwrite the file.  Under a
    #: shed storm this caps the dump cost at one write per interval
    #: instead of one per rejected request (a drain always dumps —
    #: it is the terminal artifact).
    _FLIGHT_DUMP_MIN_INTERVAL_S = 1.0

    def _flight_trigger(
        self, trigger: str, trace_id: Optional[str]
    ) -> None:
        """Dump the session's flight-recorder ring (when a dump path
        is configured and a session is active), throttled per
        ``_FLIGHT_DUMP_MIN_INTERVAL_S``.  Best-effort: the recorder
        must never take down the path that triggered it."""
        if not self.flight_dump:
            return
        rec = get_flight_recorder()
        if not rec.enabled:
            return
        now = time.perf_counter()
        if (
            trigger != "drain"
            and now - self._flight_last
            < self._FLIGHT_DUMP_MIN_INTERVAL_S
        ):
            return
        self._flight_last = now
        try:
            rec.dump(self.flight_dump, trigger, trace_id=trace_id)
        except OSError as e:
            print(
                f"service: flight dump failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )

    def health(self) -> Dict[str, Any]:
        """The ``/healthz`` document (``telemetry/export.py``):
        liveness + readiness at a glance.  ``status`` flips ``ok`` →
        ``draining`` the moment a graceful shutdown starts and
        ``drained`` once the queue has fully delivered."""
        with self._cond:
            depth = len(self._queue)
            closing = self._closing
            drained = self._drained
            sessions = len(self._sessions)
        status = (
            "drained" if drained else "draining" if closing else "ok"
        )
        with self._stats_lock:
            return {
                "status": status,
                "queue_depth": depth,
                "sessions": sessions,
                "requests": self._n_requests,
                "shed": self._n_shed,
                "errors": self._n_errors,
                "drained": drained,
            }

    def __enter__(self) -> "SolverService":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- admission -------------------------------------------------------

    def submit(
        self,
        dcop: Any = None,
        algo: Optional[str] = None,
        algo_params: Optional[Mapping[str, Any]] = None,
        *,
        rounds: int = 200,
        seed: int = 0,
        chunk_size: int = 64,
        convergence_chunks: int = 0,
        n_restarts: int = 1,
        timeout: Optional[float] = None,
        session: Optional[str] = None,
        set_values: Optional[Mapping[str, Any]] = None,
        max_util_bytes: Optional[int] = None,
        bnb: Optional[str] = None,
        table_dtype: Optional[str] = None,
        table_format: Optional[str] = None,
        trace: Optional[Mapping[str, Any]] = None,
    ) -> PendingResult:
        """Admit one solve request; returns a :class:`PendingResult`.

        ``dcop`` is a DCOP object, a yaml file path, or yaml TEXT (any
        string containing a newline is treated as text — the wire
        protocol's form).  ``session`` names a session: its first
        request must carry the dcop and pins an incremental compiler;
        later requests may omit ``dcop`` and stream ``set_values``
        deltas ({external variable: value}) instead.
        ``max_util_bytes`` (exact algorithms with a bounded-memory
        plan — DPOP) caps the request's largest UTIL table via the
        memory-bounded contraction planner (``ops/membound.py``) —
        it folds into the algorithm params, so it also partitions
        dispatch groups like any other param.  ``bnb``
        (``auto|on|off``) selects the branch-and-bound pruned
        contraction kernels the same way (an algo param of the
        algorithms with a device contraction phase — dpop, maxsum;
        results bit-identical, ``docs/semirings.md``).  ``trace`` is
        the wire
        client's trace context (``telemetry/context.py`` wire form);
        omitted, the service mints a deterministic id at admission.
        Validation errors raise HERE (before admission); dispatch
        errors surface from ``PendingResult.result()``.
        """
        t_sub = time.perf_counter()
        with self._cond:
            if self._closing:
                raise ServiceError("service is closed")
        if n_restarts < 1:
            raise ValueError(
                f"n_restarts must be >= 1, got {n_restarts}"
            )
        if set_values is not None and session is None:
            raise ValueError(
                "set_values streams external-variable deltas into a "
                "pinned session — pass session=<name> (docs/serving.md)"
            )

        sess = self._sessions.get(session) if session else None
        if sess is None and session:
            # a failed-over session's first frame on this replica:
            # promote the replicated standby copy into the live table
            # so the follow-up costs compile.incremental, not a
            # re-pin (docs/serving.md, "The fleet")
            sess = self._promote_standby(session)
        if sess is not None:
            if dcop is not None:
                # a follow-up may resend the SAME dcop (a reconnecting
                # wire client naturally re-ships its yaml text); a
                # DIFFERENT one would silently solve the pinned
                # problem under the new problem's name — reject it
                _, key = self._load_dcop(dcop)
                if key != sess.dcop_key:
                    raise ServiceError(
                        f"session {session!r} is pinned to a "
                        "different dcop — close_session first, or "
                        "use a new session name (docs/serving.md)"
                    )
            dcop_obj, dcop_key = sess.dcop, sess.dcop_key
        else:
            if dcop is None:
                raise ValueError(
                    "dcop is required (only follow-up requests of an "
                    "open session may omit it)"
                )
            dcop_obj, dcop_key = self._load_dcop(dcop)
        if algo is None:
            raise ValueError("algo is required")

        from pydcop_tpu.algorithms import (
            load_algorithm_module,
            prepare_algo_params,
            resolve_algo,
        )

        algo_name, params_in = resolve_algo(algo, algo_params)
        module = load_algorithm_module(algo_name)
        if max_util_bytes is not None:
            if not any(
                p.name == "max_util_bytes"
                for p in module.algo_params
            ):
                raise ValueError(
                    "max_util_bytes bounds the exact contraction "
                    "engine's largest UTIL table (ops/membound.py) "
                    f"— {algo_name!r} has no such table to bound"
                )
            if int(max_util_bytes) <= 0:
                raise ValueError(
                    "max_util_bytes must be > 0, got "
                    f"{max_util_bytes}"
                )
            params_in = {
                **dict(params_in or {}),
                "max_util_bytes": int(max_util_bytes),
            }
        if bnb is not None:
            if not any(
                p.name == "bnb" for p in module.algo_params
            ):
                raise ValueError(
                    "bnb selects the branch-and-bound pruned "
                    "contraction kernels — supported by algorithms "
                    "with a device contraction phase (dpop, "
                    f"maxsum); {algo_name!r} has none"
                )
            if bnb not in ("auto", "on", "off"):
                raise ValueError(
                    f"bnb must be 'auto'|'on'|'off', got {bnb!r}"
                )
            params_in = {**dict(params_in or {}), "bnb": str(bnb)}
        if table_dtype is not None:
            if not any(
                p.name == "table_dtype" for p in module.algo_params
            ):
                raise ValueError(
                    "table_dtype selects the storage precision of "
                    "packed contraction tables — supported by the "
                    "exact contraction engine (dpop); "
                    f"{algo_name!r} has none (maxsum's "
                    "message-plane sibling is msg_dtype)"
                )
            from pydcop_tpu.ops.padding import as_table_dtype

            params_in = {
                **dict(params_in or {}),
                "table_dtype": as_table_dtype(table_dtype),
            }
        if table_format is not None:
            if not any(
                p.name == "table_format"
                for p in module.algo_params
            ):
                raise ValueError(
                    "table_format selects the storage layout of "
                    "packed contraction tables — supported by the "
                    "exact contraction engine (dpop); "
                    f"{algo_name!r} has none"
                )
            from pydcop_tpu.ops.sparse import as_table_format

            params_in = {
                **dict(params_in or {}),
                "table_format": as_table_format(table_format),
            }
        params = prepare_algo_params(params_in, module.algo_params)

        req = _Request(
            dcop=dcop_obj, dcop_key=dcop_key, algo=algo_name,
            params=params, rounds=rounds, seed=seed,
            chunk_size=chunk_size,
            convergence_chunks=convergence_chunks,
            n_restarts=n_restarts, timeout=timeout, session=session,
            set_values=dict(set_values) if set_values else None,
            pending=PendingResult(),
            dcop_src=_dcop_source(dcop),
            t_sub=t_sub,
        )
        self._apply_trace(req, trace)
        return self._admit(req)

    def _apply_trace(
        self, req: _Request, trace: Optional[Mapping[str, Any]]
    ) -> None:
        """Attach the request's trace context: the wire client's when
        the frame carried one, else a service-minted id that is pure
        in the per-service admission ordinal (so in-process traffic
        stitches and replays deterministically too)."""
        parsed = parse_wire_trace(trace)
        if parsed is not None:
            req.trace_id, req.trace_span, req.trace_attempt = parsed
            return
        with self._stats_lock:
            self._trace_ordinal += 1
            ordinal = self._trace_ordinal
        req.trace_id = mint_trace_id("local", ordinal)
        req.trace_span = attempt_span_id(req.trace_id, 1)

    def _admit(self, req: _Request) -> PendingResult:
        """The one admission tail (solve and infer requests share it):
        count, overload-check under the queue lock, enqueue or shed."""
        met = get_metrics()
        if met.enabled:
            met.inc("service.requests")
        t_admit = time.perf_counter()
        if not req.t_sub:
            req.t_sub = t_admit
        req.admit_s = t_admit - req.t_sub
        shed_reason = None
        depth = 0
        with self._cond:
            if self._closing:
                raise ServiceError("service is closed")
            depth = len(self._queue)
            shed_reason = self._shed_reason_locked(req.timeout)
            if shed_reason is None:
                req.enqueue_t = t_admit
                self._queue.append(req)
                self._cond.notify_all()
        with self._stats_lock:
            self._n_requests += 1
        if shed_reason is not None:
            self._shed(req, shed_reason, depth, t_admit)
        return req.pending

    def submit_infer(
        self,
        dcop: Any = None,
        query: str = "marginals",
        *,
        order: str = "pseudo_tree",
        beta: float = 1.0,
        tol: float = 1e-6,
        device: str = "auto",
        device_min_cells: int = 1 << 14,
        timeout: Optional[float] = None,
        map_vars: Optional[Sequence[str]] = None,
        external_dists: Optional[
            Mapping[str, Mapping[Any, float]]
        ] = None,
        max_util_bytes: Optional[int] = None,
        bnb: str = "auto",
        table_dtype: str = "f32",
        table_format: str = "dense",
        trace: Optional[Mapping[str, Any]] = None,
    ) -> PendingResult:
        """Admit one inference request (``docs/semirings.md``): the
        semiring contraction queries — ``marginals`` / ``log_z`` /
        ``map`` / ``kbest:<k>`` / ``marginal_map`` (``map_vars``) /
        ``expectation`` (``external_dists``) — served by the same
        tick loop as solves.  The QUERY joins the dispatch partition
        key: a tick of mixed-query traffic coalesces each query's
        requests into ONE merged contraction sweep
        (``run_infer_many`` — per-request results bit-identical to
        sequential ``api.infer`` calls) and never mixes sweeps across
        queries.  Validation errors raise here; dispatch errors
        surface from ``PendingResult.result()``."""
        t_sub = time.perf_counter()
        with self._cond:
            if self._closing:
                raise ServiceError("service is closed")
        from pydcop_tpu.ops.semiring import (
            ELIMINATION_ORDERS,
            parse_query,
        )

        qkind, _ = parse_query(query)  # fail fast, nearest-name hint
        # the cross-field checks run_infer_many enforces must fail at
        # ADMISSION too — a doomed request must not occupy queue
        # depth and fail asynchronously a tick later
        if qkind == "marginal_map":
            if not map_vars:
                raise ValueError(
                    "marginal_map needs map_vars=[...] — the "
                    "variables maximized over"
                )
            if max_util_bytes is not None:
                raise ValueError(
                    "marginal_map cannot run memory-bounded "
                    "(docs/semirings.md, 'Structured cells')"
                )
        elif map_vars:
            raise ValueError(
                f"map_vars applies to query='marginal_map' only, "
                f"not {query!r}"
            )
        if external_dists and qkind != "expectation":
            raise ValueError(
                "external_dists applies to query='expectation' "
                f"only, not {query!r}"
            )
        if order not in ELIMINATION_ORDERS:
            raise ValueError(
                f"unknown elimination order {order!r} (expected one "
                f"of {ELIMINATION_ORDERS})"
            )
        if device not in ("auto", "never", "always"):
            raise ValueError(
                f"device must be 'auto'|'never'|'always', got "
                f"{device!r}"
            )
        if beta <= 0:
            raise ValueError(f"beta must be > 0, got {beta}")
        if max_util_bytes is not None and int(max_util_bytes) <= 0:
            raise ValueError(
                f"max_util_bytes must be > 0, got {max_util_bytes}"
            )
        if bnb not in ("auto", "on", "off"):
            raise ValueError(
                f"bnb must be 'auto'|'on'|'off', got {bnb!r}"
            )
        from pydcop_tpu.ops.padding import as_table_dtype
        from pydcop_tpu.ops.sparse import as_table_format

        table_dtype = as_table_dtype(table_dtype)  # fail at admission
        table_format = as_table_format(table_format)
        if dcop is None:
            raise ValueError("dcop is required")
        dcop_obj, dcop_key = self._load_dcop(dcop)
        req = _Request(
            dcop=dcop_obj, dcop_key=dcop_key,
            algo=f"infer:{query}", params={}, rounds=0, seed=0,
            chunk_size=0, convergence_chunks=0, n_restarts=1,
            timeout=timeout, session=None, set_values=None,
            pending=PendingResult(), dcop_src=_dcop_source(dcop),
            query=str(query),
            infer_kw={
                "order": str(order),
                "beta": float(beta),
                "tol": float(tol),
                "device": str(device),
                "device_min_cells": int(device_min_cells),
                "map_vars": (
                    tuple(map_vars) if map_vars else None
                ),
                "external_dists": (
                    {
                        str(n): dict(d)
                        for n, d in external_dists.items()
                    }
                    if external_dists
                    else None
                ),
                "max_util_bytes": (
                    int(max_util_bytes)
                    if max_util_bytes is not None
                    else None
                ),
                "bnb": str(bnb),
                "table_dtype": table_dtype,
                "table_format": table_format,
            },
        )
        req.t_sub = t_sub
        self._apply_trace(req, trace)
        return self._admit(req)

    def solve(self, *args, **kwargs) -> Dict[str, Any]:
        """Blocking convenience: ``submit(...).result()``."""
        return self.submit(*args, **kwargs).result()

    def infer(self, *args, **kwargs) -> Dict[str, Any]:
        """Blocking convenience: ``submit_infer(...).result()``."""
        return self.submit_infer(*args, **kwargs).result()

    # -- overload control ------------------------------------------------

    def _shed_reason_locked(self, timeout: Optional[float]):
        """Why this request must be shed at admission, or None.

        Two triggers (docs/serving.md): the bounded queue is full, or
        the request carries a deadline the service already knows it
        cannot meet.  ``timeout`` is the request's END-TO-END budget
        from admission (dispatch hands the remainder to the engine,
        which truncates at chunk boundaries with terminal
        ``status="timeout"``); we shed only when the predicted queue
        WAIT ALONE — full ticks queued ahead of it times the median
        recent tick duration — already consumes it, i.e. the request would
        reach dispatch with nothing left.  An idle service therefore
        never sheds, however tight the budget.  Shedding at admission
        costs microseconds; admitting a doomed request would cost a
        dispatch slot and still return nothing."""
        depth = len(self._queue)
        if depth >= self.max_queue:
            return "queue-full"
        if timeout is not None and self._tick_med:
            ticks_ahead = depth // self.tick.max_batch
            if ticks_ahead * self._tick_med >= timeout:
                return "deadline"
        return None

    def _shed(
        self,
        req: _Request,
        reason: str,
        depth: int,
        t_admit: float,
    ) -> None:
        met = get_metrics()
        tr = get_tracer()
        latency = time.perf_counter() - t_admit
        if met.enabled:
            met.inc("service.shed")
            met.observe(
                "service.shed_latency_s", latency,
                buckets=_LATENCY_BUCKETS,
            )
        if tr.enabled:
            tr.event(
                "service-shed", cat="service", reason=reason,
                algo=req.algo, queue_depth=depth,
                trace=req.trace_id,
            )
        with self._stats_lock:
            self._n_shed += 1
            self._shed_lats.append(latency)
        req.pending._set_result(
            {
                "status": "shed",
                "shed_reason": reason,
                "queue_depth": depth,
                "algo": req.algo,
                "trace": req.trace_id,
                # a shed never reaches dispatch: the whole breakdown
                # is the admission segment
                "phases": {"admission": req.admit_s + latency},
            }
        )
        self._flight_trigger("shed", req.trace_id)

    # -- wire-server hooks (ServiceServer bookkeeping) -------------------

    def note_shed(self, reason: str) -> None:
        """Record a request shed BEFORE admission (the wire server's
        per-connection in-flight cap) so overload shows up in one
        place regardless of which layer refused the work."""
        met = get_metrics()
        if met.enabled:
            met.inc("service.shed")
        tr = get_tracer()
        if tr.enabled:
            tr.event("service-shed", cat="service", reason=reason)
        with self._stats_lock:
            self._n_shed += 1

    def note_frame_rejected(self) -> None:
        met = get_metrics()
        if met.enabled:
            met.inc("service.frames_rejected")
        with self._stats_lock:
            self._n_frames_rejected += 1

    def note_replayed_reply(self) -> None:
        met = get_metrics()
        if met.enabled:
            met.inc("service.replayed_replies")
        with self._stats_lock:
            self._n_replayed_replies += 1

    def close_session(self, name: str) -> bool:
        """Drop a pinned session (frees its compiled state); returns
        whether it existed.  A replicated standby copy of the same
        name drops too — a closed session must not resurrect through
        a later promotion."""
        with self._repl_lock:
            self._standby_sessions.pop(name, None)
        with self._cond:
            return self._sessions.pop(name, None) is not None

    # -- session checkpoint / restore ------------------------------------

    def write_session_checkpoint(self, path: str) -> Dict[str, Any]:
        """Serialize every restorable pinned session — the dcop
        identity (yaml text or path), the ORDERED applied
        ``set_values`` deltas, and the per-session segment counter —
        plus a final :meth:`stats` snapshot, atomically, to ``path``
        (JSON).  ``serve --resume`` replays it so reconnecting
        sessions' follow-ups stay ``compile.incremental``-only.

        Object-pinned in-process sessions serialize through
        ``dcop_yaml`` when possible; ones that cannot are listed under
        ``"skipped"`` instead of failing the drain."""
        with self._cond:
            sessions = dict(self._sessions)
        entries: List[Dict[str, Any]] = []
        skipped: List[str] = []
        for name, sess in sessions.items():
            src = sess.source
            if src is None:
                try:
                    from pydcop_tpu.dcop.yamldcop import dcop_yaml

                    src = ("yaml", dcop_yaml(sess.dcop))
                except Exception:  # noqa: BLE001 — not every
                    # in-process constraint round-trips through yaml
                    skipped.append(name)
                    continue
            entries.append(
                {
                    "name": name,
                    "source": list(src),
                    "deltas": sess.deltas,
                    "segments": sess.segments,
                    "exact": _exact_doc(sess),
                }
            )
        doc = {
            "kind": "pydcop_tpu-service-sessions",
            "version": 1,
            # canonical JSON-safe form: pad_policy may be a PadPolicy
            # OBJECT (as_pad_policy accepts both) and must still
            # checkpoint — and compare equal at resume regardless of
            # which spelling either side used
            "pad_policy": _pad_policy_doc(self.pad_policy),
            "sessions": entries,
            "skipped": skipped,
            "stats": self.stats(),
        }
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        tr = get_tracer()
        if tr.enabled:
            tr.event(
                "service-checkpoint", cat="service",
                sessions=len(entries), skipped=len(skipped),
            )
        return doc

    def restore_sessions(self, path: str) -> int:
        """Replay a drained service's session checkpoint: rebuild each
        session's dcop, pin a fresh IncrementalCompiler, and re-apply
        the recorded ``set_values`` deltas IN ORDER — the exact
        incremental-update arithmetic of the original service, so a
        reconnecting client's next follow-up lands on bit-identical
        device tables and costs ``compile.incremental`` only (the
        replay itself pays the one segment-1 ``compile.full``, at
        startup, before any request is admitted).  Returns the number
        of sessions restored.

        The three broken-checkpoint shapes fail with STRUCTURED
        errors (missing file, truncated/non-JSON content, schema
        drift) — ``serve --resume`` surfaces them as a clean exit, so
        a fleet health watcher sees the replica as dead instead of a
        hung or silently-empty one."""
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except FileNotFoundError:
            raise ServiceError(
                f"session checkpoint {path} does not exist — the "
                "previous run never drained there (or the path is "
                "wrong); start without resume, or point at the real "
                "checkpoint"
            ) from None
        except ValueError as e:
            raise ServiceError(
                f"session checkpoint {path} is not valid JSON "
                f"(truncated or corrupted write?): {e}"
            ) from None
        if (
            not isinstance(doc, dict)
            or doc.get("kind") != "pydcop_tpu-service-sessions"
        ):
            raise ServiceError(
                f"{path} is not a service session checkpoint"
            )
        if doc.get("version") != 1:
            raise ServiceError(
                f"session checkpoint {path} has schema version "
                f"{doc.get('version')!r}, this build reads version 1 "
                "— re-drain under the current build (docs/serving.md)"
            )
        if doc.get("pad_policy") != _pad_policy_doc(self.pad_policy):
            raise ServiceError(
                f"checkpoint was written under pad_policy="
                f"{doc.get('pad_policy')!r}, the service runs "
                f"{_pad_policy_doc(self.pad_policy)!r} — resumed "
                "sessions would land in different shape buckets "
                "(docs/serving.md)"
            )
        restored = 0
        skipped: List[Tuple[str, str]] = []
        for entry in doc.get("sessions", ()):
            # per-entry tolerance, mirroring the write side's
            # "skipped" list: one stale entry (a cleaned-up yaml
            # path, a since-invalid dcop) must not abort the whole
            # resume and lose every OTHER session
            try:
                name, sess = self._build_session_from_entry(entry)
            except Exception as e:  # noqa: BLE001 — skip, record
                skipped.append(
                    (
                        str(entry.get("name")),
                        f"{type(e).__name__}: {e}"[:200],
                    )
                )
                continue
            with self._cond:
                self._sessions[name] = sess
            restored += 1
        if skipped:
            tr = get_tracer()
            if tr.enabled:
                tr.event(
                    "service-restore-skipped", cat="service",
                    sessions=[n for n, _ in skipped],
                    errors=[err for _, err in skipped],
                )
        met = get_metrics()
        if met.enabled and restored:
            met.inc("service.sessions_restored", restored)
        with self._stats_lock:
            self._n_sessions_restored += restored
        tr = get_tracer()
        if tr.enabled:
            tr.event(
                "service-restore", cat="service", sessions=restored,
            )
        return restored

    def _build_session_from_entry(
        self, entry: Mapping[str, Any]
    ) -> Tuple[str, _Session]:
        """Rebuild one checkpoint/replication session entry through
        the restore replay: load the dcop from its serialized
        identity, pin a fresh IncrementalCompiler, pay the one
        segment-1 ``compile.full``, and re-apply the recorded deltas
        IN ORDER (``compile.incremental`` each) — bit-identical
        device tables to the service that wrote the entry."""
        from pydcop_tpu.engine.incremental import IncrementalCompiler

        name = str(entry["name"])
        kind, val = entry["source"]
        if kind == "yaml":
            from pydcop_tpu.dcop.yamldcop import load_dcop

            dcop = load_dcop(val)
            key: Tuple = (
                "yaml",
                hashlib.sha256(val.encode("utf-8")).hexdigest(),
            )
        elif kind == "path":
            from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

            dcop = load_dcop_from_file(val)
            st = os.stat(os.path.realpath(val))
            key = (
                "path", os.path.realpath(val),
                st.st_mtime_ns, st.st_size,
            )
        else:
            raise ServiceError(f"unknown source kind {kind!r}")
        compiler = IncrementalCompiler(
            dcop, pad_policy=self.pad_policy
        )
        sess = _Session(compiler, dcop, key, source=(kind, val))
        ext: Dict[str, Any] = {}
        compiler.compile({}, ext)  # segment 1 (the one full)
        for delta in entry.get("deltas", ()):
            ext.update(delta)
            compiler.compile({}, ext)  # replayed incremental
        sess.ext_values = ext
        sess.deltas = [dict(d) for d in entry.get("deltas", ())]
        sess.segments = int(entry.get("segments", 0))
        # warm the memoized exact sessions the entry recorded: ONE
        # solve at the final accumulated state re-fills the message
        # memo and pre-warms the 1-row kernels (engine/memo.py), so
        # the session's first LIVE follow-up is already an O(delta)
        # memo re-solve — the exact-path analogue of the replayed
        # compile.incremental contract above
        for algo, params in (entry.get("exact") or {}).items():
            if str(algo) != "dpop":
                continue
            try:
                from pydcop_tpu.engine.memo import ExactSession

                es = ExactSession(
                    dcop,
                    pad_policy=self.pad_policy,
                    memo_bytes=self.session_memo_bytes,
                )
                if ext:
                    es.set_values(ext)
                es.solve(dict(params or {}))
            except Exception:  # noqa: BLE001 — the warm replay is
                # an optimization; the live path rebuilds lazily
                continue
            sess.exact[str(algo)] = es
            sess.exact_params[str(algo)] = dict(params or {})
        return name, sess

    # -- fleet replication (docs/serving.md, "The fleet") ----------------

    def session_entry(self, name: str) -> Optional[Dict[str, Any]]:
        """One session's replication entry — exactly the checkpoint
        schema (serialized dcop identity + ordered delta log + segment
        counter), so the standby applies it through the SAME restore
        replay the checkpoint/resume contract already pins as
        bit-identical.  None when the session does not exist or its
        in-process dcop cannot serialize."""
        with self._cond:
            sess = self._sessions.get(name)
        if sess is None:
            return None
        src = sess.source
        if src is None:
            try:
                from pydcop_tpu.dcop.yamldcop import dcop_yaml

                src = ("yaml", dcop_yaml(sess.dcop))
            except Exception:  # noqa: BLE001 — same tolerance as
                # the checkpoint writer's "skipped" list
                return None
        return {
            "name": name,
            "source": list(src),
            "deltas": [dict(d) for d in sess.deltas],
            "segments": sess.segments,
            "exact": _exact_doc(sess),
        }

    def set_standbys(self, addrs: Sequence[str]) -> int:
        """Configure this replica's replication targets (its hash-ring
        successors — the fleet controller computes them from
        ``engine.fleet.standby_map``) and WARM them: every currently
        live session re-streams immediately, so a standby attached
        late (a rebalance, a restarted fleet member) holds a full
        copy before the next failover could need it.  Returns the
        number of sessions streamed."""
        clean = [str(a) for a in addrs]
        with self._repl_lock:
            old = list(self._repl_clients.values())
            self._repl_clients = {}
            self._standby_addrs = clean
        for cli in old:
            cli.close()
        with self._cond:
            names = list(self._sessions)
        for name in names:
            self.replicate_session(name)
        return len(names)

    def replicate_session(
        self,
        name: str,
        cache: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Stream one session's current replication entry to every
        configured standby (the wire server calls this after each
        delivered session reply, BEFORE the reply reaches the client
        — so any reply a client observed is already recoverable).
        ``cache`` piggybacks the delivered ``(ikey, reply)`` pair so
        the standby pre-populates its reply cache: a failover retry
        of an answered request replays there instead of re-solving.
        A session that no longer exists streams a tombstone (the
        standby drops its copy).  Best-effort per standby: a
        replication failure is counted and traced, never raised into
        the delivery path."""
        with self._repl_lock:
            if not self._standby_addrs:
                return
        entry = self.session_entry(name)
        if entry is None:
            entry = {"name": name, "closed": True}
        with self._repl_lock:
            addrs = list(self._standby_addrs)
            for addr in addrs:
                self._replicate_to_locked(addr, entry, cache)

    def _replicate_to_locked(
        self,
        addr: str,
        entry: Dict[str, Any],
        cache: Optional[Dict[str, Any]],
    ) -> None:
        met = get_metrics()
        try:
            cli = self._repl_clients.get(addr)
            if cli is None:
                cli = ServiceClient(
                    addr, timeout=5.0, retry_window=0.5
                )
                self._repl_clients[addr] = cli
            cli._call("replicate", entry=entry, cache=cache)
        except (ServiceError, OSError) as e:
            # drop the client so the next entry reconnects fresh; the
            # standby re-syncs from the full delta log it carries
            stale = self._repl_clients.pop(addr, None)
            if stale is not None:
                stale.close()
            with self._stats_lock:
                self._n_replication_errors += 1
            if met.enabled:
                met.inc("service.replication_errors")
            tr = get_tracer()
            if tr.enabled:
                tr.event(
                    "service-replication-error", cat="service",
                    standby=addr, session=entry.get("name"),
                    error=f"{type(e).__name__}: {e}"[:200],
                )
            return
        with self._stats_lock:
            self._n_replicated_segments += 1
        if met.enabled:
            met.inc("service.replicated_segments")

    def apply_replica_entry(
        self, entry: Mapping[str, Any]
    ) -> Dict[str, Any]:
        """Apply one replicated session entry as a STANDBY (the
        ``replicate`` wire op).  When the entry's delta log extends
        the copy we already hold (same source, our applied deltas are
        a prefix), only the tail replays — ``compile.incremental``
        per new delta, zero fulls; anything else (first sight, a
        diverged log, a re-pinned source) rebuilds through the
        checkpoint-restore replay.  A ``closed`` tombstone drops the
        copy."""
        name = str(entry.get("name"))
        if not name:
            raise ServiceError("replicate entry has no session name")
        if entry.get("closed"):
            with self._repl_lock:
                self._standby_sessions.pop(name, None)
            return {"mode": "closed", "segments": 0}
        deltas = [dict(d) for d in entry.get("deltas", ())]
        with self._repl_lock:
            sess = self._standby_sessions.get(name)
            if (
                sess is not None
                and list(sess.source or ())
                == list(entry.get("source", ()))
                and sess.deltas == deltas[: len(sess.deltas)]
                and int(entry.get("segments", 0)) >= sess.segments
            ):
                mode = "incremental"
                for delta in deltas[len(sess.deltas):]:
                    sess.ext_values.update(delta)
                    sess.compiler.compile({}, sess.ext_values)
                sess.deltas = deltas
                sess.segments = int(
                    entry.get("segments", sess.segments)
                )
                # standby exact sessions follow the tail WITHOUT
                # re-solving: set_values re-tabulates only touched
                # constraints, so the memo (warm since the rebuild
                # solve) serves the promoted session's first
                # follow-up as an O(tail) re-contraction
                sess.exact_params.update(
                    {
                        str(a): dict(p or {})
                        for a, p in (
                            entry.get("exact") or {}
                        ).items()
                    }
                )
                for es in list(sess.exact.values()):
                    try:
                        es.set_values(sess.ext_values)
                    except Exception:  # noqa: BLE001 — drop the
                        # copies; promotion rebuilds lazily
                        sess.exact.clear()
                        break
            else:
                mode = "rebuild"
                name, sess = self._build_session_from_entry(entry)
                self._standby_sessions[name] = sess
        with self._stats_lock:
            self._n_replica_updates += 1
        met = get_metrics()
        if met.enabled:
            met.inc("service.replica_updates")
        return {"mode": mode, "segments": sess.segments}

    def _promote_standby(self, name: str) -> Optional["_Session"]:
        """Move a replicated standby copy into the LIVE session table
        — the failed-over session's first frame lands here, and its
        follow-up must cost ``compile.incremental`` exactly as it
        would have on the dead owner."""
        with self._repl_lock:
            sess = self._standby_sessions.pop(name, None)
        if sess is None:
            return None
        with self._cond:
            live = self._sessions.setdefault(name, sess)
        if live is sess:
            with self._stats_lock:
                self._n_sessions_promoted += 1
            met = get_metrics()
            if met.enabled:
                met.inc("service.sessions_promoted")
            tr = get_tracer()
            if tr.enabled:
                tr.event(
                    "service-promote", cat="service", session=name,
                    segments=sess.segments,
                )
        return live

    # -- stats -----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Always-on serving aggregates: request/tick/dispatch counts,
        coalesce ratio, occupancy and queue-wait/latency percentiles
        over a bounded recent window."""
        with self._stats_lock:
            waits = list(self._queue_waits)
            lats = list(self._latencies)
            occs = [float(o) for o in self._occupancies]
            shed_lats = list(self._shed_lats)
            out = {
                "requests": self._n_requests,
                "ticks": self._n_ticks,
                "dispatches": self._n_dispatches,
                "coalesced_requests": self._n_coalesced,
                "pad_instances": self._n_pad_instances,
                "errors": self._n_errors,
                "shed": self._n_shed,
                "frames_rejected": self._n_frames_rejected,
                "sessions_restored": self._n_sessions_restored,
                "replayed_replies": self._n_replayed_replies,
                "replica_updates": self._n_replica_updates,
                "replicated_segments": self._n_replicated_segments,
                "replication_errors": self._n_replication_errors,
                "sessions_promoted": self._n_sessions_promoted,
                "standby_sessions": len(self._standby_sessions),
                "sessions": len(self._sessions),
                "queue_depth": len(self._queue),
                "drained": self._drained,
            }
        out["coalesce_ratio"] = (
            round(len(lats) and sum(occs) / max(1, len(occs)), 4)
            if occs
            else 0.0
        )
        out["queue_wait_s"] = {
            "p50": _percentile(waits, 50),
            "p99": _percentile(waits, 99),
            "max": max(waits) if waits else 0.0,
        }
        out["latency_s"] = {
            "p50": _percentile(lats, 50),
            "p99": _percentile(lats, 99),
            "max": max(lats) if lats else 0.0,
        }
        out["batch_occupancy"] = {
            "p50": _percentile(occs, 50),
            "max": max(occs) if occs else 0.0,
        }
        # admission-to-reject latency: the overload acceptance wants a
        # BOUNDED p99 here — shedding must stay cheap under pressure
        out["shed_latency_s"] = {
            "p50": _percentile(shed_lats, 50),
            "p99": _percentile(shed_lats, 99),
            "max": max(shed_lats) if shed_lats else 0.0,
        }
        return out

    # -- dcop loading + compiled-problem cache ---------------------------

    def _load_dcop(self, dcop: Any) -> Tuple[Any, Tuple]:
        """Normalize a request's dcop to (DCOP object, cache key).

        yaml TEXT keys by content hash (repeat submissions of the same
        text share one compile), paths by (realpath, mtime, size),
        objects by identity (the cache entry pins the object, so the
        id can never be recycled under the key)."""
        from pydcop_tpu.dcop.dcop import DCOP

        if isinstance(dcop, DCOP):
            return dcop, ("obj", id(dcop))
        if isinstance(dcop, str) and "\n" in dcop:
            key = (
                "yaml",
                hashlib.sha256(dcop.encode("utf-8")).hexdigest(),
            )
            with self._cond:
                cached = self._compiled.get(key)
            if cached is not None:
                return cached[0], key
            from pydcop_tpu.dcop.yamldcop import load_dcop

            return load_dcop(dcop), key
        if isinstance(dcop, (str, list, tuple)):
            from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

            if isinstance(dcop, str):
                path = os.path.realpath(dcop)
                st = os.stat(path)
                key = ("path", path, st.st_mtime_ns, st.st_size)
                with self._cond:
                    cached = self._compiled.get(key)
                if cached is not None:
                    return cached[0], key
            else:
                key = ("paths", tuple(dcop))
            return load_dcop_from_file(dcop), key
        raise ValueError(
            f"dcop must be a DCOP object, a yaml path, or yaml text — "
            f"got {type(dcop).__name__}"
        )

    def _compiled_problem(self, req: _Request):
        """The request's CompiledProblem, from the LRU cache when the
        dcop identity was seen before (the host-side analogue of the
        runner cache: repeated requests skip the numpy re-tabulation,
        not just the XLA compile)."""
        key = req.dcop_key
        with self._cond:
            hit = self._compiled.get(key)
            if hit is not None and (
                key[0] != "obj" or hit[0] is req.dcop
            ):
                self._compiled.move_to_end(key)
                return hit[1]
        from pydcop_tpu.ops.compile import compile_dcop

        problem = compile_dcop(req.dcop, pad_policy=self.pad_policy)
        with self._cond:
            self._compiled[key] = (req.dcop, problem)
            while len(self._compiled) > self._compile_cache_max:
                self._compiled.popitem(last=False)
        return problem

    # -- the tick loop ---------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closing:
                    self._cond.wait()
                if not self._queue:
                    return  # closing, drained
                # tick policy: fire on max_batch pending, or when the
                # oldest request has waited max_wait
                while (
                    len(self._queue) < self.tick.max_batch
                    and not self._closing
                ):
                    left = self.tick.max_wait - (
                        time.perf_counter() - self._queue[0].enqueue_t
                    )
                    if left <= 0:
                        break
                    self._cond.wait(timeout=left)
                batch = [
                    self._queue.popleft()
                    for _ in range(
                        min(len(self._queue), self.tick.max_batch)
                    )
                ]
            tick_t0 = time.perf_counter()
            try:
                self._dispatch_tick(batch)
            except Exception as e:  # noqa: BLE001 — the worker must
                # outlive ANY tick (an escaped telemetry/bookkeeping
                # error would otherwise kill the thread silently and
                # leave every future request queued forever): fail
                # the batch's undelivered requests, keep ticking
                try:
                    self._fail(batch, e)
                except Exception:  # noqa: BLE001 — even the failure
                    # path (tracer/metrics) can be what's broken;
                    # unblocking the clients is the one hard duty left
                    for req in batch:
                        if not req.pending.done():
                            req.pending._set_error(
                                ServiceError(
                                    f"tick dispatch failed: "
                                    f"{type(e).__name__}: {e}"
                                )
                            )
            # feed the deadline-aware shed's capacity estimate: how
            # long a tick of work actually takes right now
            dur = time.perf_counter() - tick_t0
            with self._cond:
                self._tick_durs.append(dur)
                self._tick_med = _percentile(
                    list(self._tick_durs), 50
                )

    def _dispatch_tick(self, batch: List[_Request]) -> None:
        from pydcop_tpu.engine.supervisor import supervision

        met = get_metrics()
        tr = get_tracer()
        tick_t = time.perf_counter()
        for req in batch:
            req.queue_wait = tick_t - req.enqueue_t
            if met.enabled:
                met.observe(
                    "service.queue_wait_s", req.queue_wait,
                    buckets=_LATENCY_BUCKETS,
                )
            if tr.enabled:
                tr.add_span(
                    "service.queue-wait", "service", req.enqueue_t,
                    req.queue_wait, algo=req.algo,
                    trace=req.trace_id,
                )
        with self._stats_lock:
            self._n_ticks += 1
            self._queue_waits.extend(r.queue_wait for r in batch)
        if met.enabled:
            met.inc("service.ticks")
            met.gauge("service.queue_depth", len(self._queue))

        # session requests keep FIFO order per session; stateless
        # solves coalesce into groups; infer requests partition by
        # QUERY (plus knobs) and merge per partition
        with supervision(self._sup):
            stateless: List[_Request] = []
            infer_reqs: List[_Request] = []
            for req in batch:
                if req.query is not None:
                    infer_reqs.append(req)
                elif req.session is not None:
                    self._dispatch_session(req)
                else:
                    stateless.append(req)
            if stateless:
                self._dispatch_groups(stateless)
            if infer_reqs:
                self._dispatch_infer_groups(infer_reqs)

    # -- dispatch: coalesced stateless groups ----------------------------

    def _group_key(self, req: _Request) -> Tuple:
        from pydcop_tpu.engine.host_batch import statics_signature

        return (
            req.algo,
            statics_signature(req.params),
            req.rounds,
            req.chunk_size,
            req.convergence_chunks,
            req.n_restarts,
            # timeouts act GROUP-wide at chunk boundaries
            # (run_many_batched), so a request carrying one may only
            # coalesce with requests carrying the same one — a tight
            # deadline must never truncate a batchmate's solve
            req.timeout,
        )

    def _dispatch_groups(self, reqs: List[_Request]) -> None:
        partitions: "OrderedDict[Tuple, List[_Request]]" = OrderedDict()
        for req in reqs:
            partitions.setdefault(self._group_key(req), []).append(req)
        for part in partitions.values():
            from pydcop_tpu.algorithms import load_algorithm_module

            module = load_algorithm_module(part[0].algo)
            try:
                if hasattr(module, "solve_host"):
                    self._dispatch_host(part, module)
                else:
                    self._dispatch_device(part, module)
            except Exception as e:  # noqa: BLE001 — fail this
                # partition's requests, keep serving the others
                self._fail(part, e)

    def _finish(
        self, req: _Request, result: Dict[str, Any], group_n: int
    ) -> None:
        met = get_metrics()
        tr = get_tracer()
        now = time.perf_counter()
        latency = now - req.enqueue_t
        result["queue_wait"] = req.queue_wait
        result["instances_batched"] = group_n
        result.pop("telemetry", None)  # service-level, not per-request
        result["trace"] = req.trace_id
        # the per-request phase breakdown (docs/observability.md,
        # "Serving observability"): contiguous segments from submit
        # entry to this delivery — their sum is the server-side share
        # of the client-observed latency.  `queue` runs from enqueue
        # to the request's GROUP starting to process (so a late group
        # in a multi-group tick reports its true wait, not the tick's
        # start), `decode` from the device sync to this delivery.
        phases = {
            "admission": round(req.admit_s, 6),
            "queue": round(
                max(
                    (req.dispatch_t or now) - req.enqueue_t, 0.0
                ),
                6,
            ),
            "compile": round(req.compile_s, 6),
            "device": round(req.device_s, 6),
            "decode": round(
                max(now - req.decode_t0, 0.0) if req.decode_t0 else 0.0,
                6,
            ),
        }
        result["phases"] = phases
        if met.enabled:
            met.observe(
                "service.latency_s", latency, buckets=_LATENCY_BUCKETS
            )
            if group_n > 1:
                met.inc("service.coalesced")
            # deterministic work delivered (FAQ cost-model unit):
            # UTIL/contraction cells for exact solves ("util_cells" on
            # dpop results, "cells" on infer results) — feeds the
            # cells/s column in `top` and the perf-drift tooling
            # (docs/performance.md)
            cells = result.get("util_cells") or result.get("cells")
            if isinstance(cells, (int, float)) and cells > 0:
                met.inc("service.work_cells", int(cells))
        if tr.enabled:
            tr.add_span(
                "service.request", "service", req.enqueue_t, latency,
                algo=req.algo, instances=group_n,
                status=result.get("status"), trace=req.trace_id,
                phases=phases,
            )
        with self._stats_lock:
            self._latencies.append(latency)
            if group_n > 1:
                self._n_coalesced += 1
        req.pending._set_result(result)
        if result.get("status") == "degraded":
            # a quarantined lane: the evidence of WHY (the nan_inject
            # fault event, the supervisor actions, the batchmates'
            # spans) is on the ring right now — dump it
            self._flight_trigger("quarantine", req.trace_id)

    def _fail(self, reqs: List[_Request], error: BaseException) -> None:
        # a partition can span several stacked groups; groups that
        # already delivered must keep their results when a LATER
        # group's dispatch raises
        reqs = [r for r in reqs if not r.pending.done()]
        if not reqs:
            return
        met = get_metrics()
        tr = get_tracer()
        if tr.enabled:
            tr.event(
                "service-error", cat="service",
                error=f"{type(error).__name__}: {error}"[:300],
                requests=len(reqs),
                trace=[r.trace_id for r in reqs if r.trace_id]
                or None,
            )
        if met.enabled:
            met.inc("service.errors", len(reqs))
        with self._stats_lock:
            self._n_errors += len(reqs)
        for req in reqs:
            req.pending._set_error(
                ServiceError(
                    f"dispatch failed for algo={req.algo!r}: "
                    f"{type(error).__name__}: {error}"
                )
            )
        # an unrecoverable dispatch is the flight recorder's reason to
        # exist: the failing group's spans + supervisor events are on
        # the ring, the reply only carries the error string
        self._flight_trigger("error", reqs[0].trace_id)

    def _record_dispatch(self, k: int, padded: int) -> None:
        met = get_metrics()
        if met.enabled:
            met.inc("service.dispatches")
            met.observe(
                "service.batch_occupancy", k,
                buckets=(1, 2, 4, 8, 16, 32, 64, 128),
            )
            if padded:
                met.inc("service.pad_instances", padded)
        with self._stats_lock:
            self._n_dispatches += 1
            self._occupancies.append(k)
            self._n_pad_instances += padded

    def _dispatch_device(self, part: List[_Request], module) -> None:
        from pydcop_tpu.api import _result_dict
        from pydcop_tpu.engine.batched import run_many_batched
        from pydcop_tpu.ops.compile import stack_problems

        tr = get_tracer()
        r0 = part[0]
        # phase attribution: the first group's `compile` segment opens
        # at the partition's problem-compile, later groups' at their
        # own iteration start (their wait behind earlier groups'
        # device runs is queue time, which `dispatch_t` delimits)
        t_part0 = time.perf_counter()
        problems = [self._compiled_problem(r) for r in part]
        first_group = True
        for stacked in stack_problems(problems):
            g0 = t_part0 if first_group else time.perf_counter()
            first_group = False
            group = [part[i] for i in stacked.indices]
            k = len(group)
            # occupancy bucketing: pad the group to a pow-2 instance
            # count by repeating the last member so the vmapped runner
            # cache (keyed on K) converges on log2 executables instead
            # of one per distinct tick size; pad lanes re-solve a real
            # instance and are discarded below
            padded = 0
            if self.instance_bucket == "pow2" and k > 1:
                k_pad = _next_pow2(k)
                if k_pad != k:
                    padded = k_pad - k
                    stacked = stack_problems(
                        stacked.host_problems
                        + [stacked.host_problems[-1]] * padded
                    )[0]
            # the group key pins one shared timeout per partition
            run_timeout = None
            if r0.timeout is not None:
                run_timeout = max(
                    r0.timeout
                    - (time.perf_counter() - r0.enqueue_t),
                    0.01,
                )
            self._record_dispatch(k, padded)
            params_list = [g.params for g in group]
            seeds = [g.seed for g in group]
            if padded:
                params_list = params_list + [params_list[-1]] * padded
                seeds = seeds + [seeds[-1]] * padded
            t_run0 = time.perf_counter()
            for req in group:
                req.dispatch_t = g0
                req.compile_s = t_run0 - g0
            # every span/event recorded inside the dispatch — the
            # dispatch span itself, supervisor retries/faults,
            # quarantine events — tags with the group's trace ids
            with trace_scope([g.trace_id for g in group]):
                with tr.span(
                    "service.dispatch", cat="service", instances=k,
                    padded=padded, algo=r0.algo,
                ):
                    results = run_many_batched(
                        stacked,
                        module,
                        params_list,
                        rounds=r0.rounds,
                        seeds=seeds,
                        timeout=run_timeout,
                        chunk_size=r0.chunk_size,
                        convergence_chunks=r0.convergence_chunks,
                        n_restarts=r0.n_restarts,
                    )
            t_done = time.perf_counter()
            for req in group:
                req.device_s = t_done - t_run0
                req.decode_t0 = t_done
            for req, rr in zip(group, results):  # pads fall off zip
                out = _result_dict(rr)
                out["time"] = rr.time / k
                self._finish(req, out, k)

    def _dispatch_host(self, part: List[_Request], module) -> None:
        """Exact host-path algorithms (DPOP, SyncBB): one
        ``run_many_host`` call per partition — DPOP requests merge
        their UTIL sweeps exactly as ``api.solve_many`` merges them."""
        from pydcop_tpu.engine.host_batch import run_many_host

        tr = get_tracer()
        r0 = part[0]
        k = len(part)
        # the group key pins one shared timeout per partition
        run_timeout = None
        if r0.timeout is not None:
            run_timeout = max(
                r0.timeout - (time.perf_counter() - r0.enqueue_t),
                0.01,
            )
        self._record_dispatch(k, 0)
        t_run0 = time.perf_counter()
        for req in part:
            # host-path phase attribution: compile (dcop -> tables)
            # happens inside run_many_host, inseparable from the
            # sweep — the whole call is the `device` segment
            req.dispatch_t = t_run0
            req.compile_s = 0.0
        with trace_scope([g.trace_id for g in part]):
            with tr.span(
                "service.dispatch", cat="service", instances=k,
                padded=0, algo=r0.algo,
            ):
                results = run_many_host(
                    [g.dcop for g in part],
                    module,
                    [g.params for g in part],
                    timeout=run_timeout,
                    pad_policy=self.pad_policy,
                )
        t_done = time.perf_counter()
        for req in part:
            req.device_s = t_done - t_run0
            req.decode_t0 = t_done
        for req, out in zip(part, results):
            self._finish(req, out, out.get("instances_batched", k))

    # -- dispatch: coalesced inference partitions ------------------------

    def _infer_group_key(self, req: _Request) -> Tuple:
        """The infer dispatch partition key: QUERY first — mixed-query
        traffic in one tick must coalesce per query, never across —
        then every knob that changes the sweep's arithmetic or its
        group-wide timeout."""
        kw = req.infer_kw
        ed = kw.get("external_dists")
        ed_key = (
            None
            if not ed
            else tuple(
                sorted(
                    (
                        n,
                        tuple(
                            sorted(
                                (str(v), float(p))
                                for v, p in d.items()
                            )
                        ),
                    )
                    for n, d in ed.items()
                )
            )
        )
        return (
            "infer", req.query, kw["order"], kw["beta"], kw["tol"],
            kw["device"], kw["device_min_cells"], kw["map_vars"],
            ed_key, kw["max_util_bytes"], kw.get("bnb", "auto"),
            kw.get("table_dtype", "f32"),
            kw.get("table_format", "dense"), req.timeout,
        )

    def _dispatch_infer_groups(self, reqs: List[_Request]) -> None:
        partitions: "OrderedDict[Tuple, List[_Request]]" = (
            OrderedDict()
        )
        for req in reqs:
            partitions.setdefault(
                self._infer_group_key(req), []
            ).append(req)
        for part in partitions.values():
            try:
                self._dispatch_infer(part)
            except Exception as e:  # noqa: BLE001 — fail this
                # partition's requests, keep serving the others
                self._fail(part, e)

    def _dispatch_infer(self, part: List[_Request]) -> None:
        """One merged ``run_infer_many`` sweep per infer partition:
        same-bucket contractions from different requests share one
        vmapped dispatch, and per-request results are bit-identical
        to sequential ``api.infer`` calls (the solve_many contract)."""
        from pydcop_tpu.ops.semiring import run_infer_many

        tr = get_tracer()
        r0 = part[0]
        kw = r0.infer_kw
        k = len(part)
        run_timeout = None
        if r0.timeout is not None:
            run_timeout = max(
                r0.timeout - (time.perf_counter() - r0.enqueue_t),
                0.01,
            )
        self._record_dispatch(k, 0)
        mv = kw["map_vars"]
        t_run0 = time.perf_counter()
        for req in part:
            req.dispatch_t = t_run0
            req.compile_s = 0.0  # plan+kernels build inside the sweep
        with trace_scope([g.trace_id for g in part]):
            with tr.span(
                "service.dispatch", cat="service", instances=k,
                padded=0, algo=r0.algo,
            ):
                results = run_infer_many(
                    [g.dcop for g in part],
                    r0.query,
                    order=kw["order"],
                    beta=kw["beta"],
                    tol=kw["tol"],
                    device=kw["device"],
                    device_min_cells=kw["device_min_cells"],
                    pad_policy=self.pad_policy,
                    timeout=run_timeout,
                    max_util_bytes=kw["max_util_bytes"],
                    map_vars=list(mv) if mv else None,
                    external_dists=kw["external_dists"],
                    bnb=kw.get("bnb", "auto"),
                    table_dtype=kw.get("table_dtype", "f32"),
                    table_format=kw.get("table_format", "dense"),
                )
        t_done = time.perf_counter()
        for req in part:
            req.device_s = t_done - t_run0
            req.decode_t0 = t_done
        for req, out in zip(part, results):
            self._finish(req, out, k)

    # -- dispatch: session-affine requests -------------------------------

    def _dispatch_session(self, req: _Request) -> None:
        try:
            result = self._solve_session(req)
        except Exception as e:  # noqa: BLE001 — per-request failure
            self._fail([req], e)
            return
        self._finish(req, result, 1)

    def _solve_session(self, req: _Request) -> Dict[str, Any]:
        from pydcop_tpu.api import _result_dict
        from pydcop_tpu.engine.batched import run_batched

        tr = get_tracer()
        sess = self._sessions.get(req.session)
        if sess is None:
            from pydcop_tpu.engine.incremental import (
                IncrementalCompiler,
            )

            sess = _Session(
                IncrementalCompiler(
                    req.dcop, pad_policy=self.pad_policy
                ),
                req.dcop,
                req.dcop_key,
                source=req.dcop_src,
            )
            self._sessions[req.session] = sess
            met = get_metrics()
            if met.enabled:
                met.inc("service.sessions_opened")
        if req.set_values:
            unknown = set(req.set_values) - set(
                sess.dcop.external_variables
            )
            if unknown:
                raise ServiceError(
                    f"set_values names {sorted(unknown)}, not external "
                    "variables of the session's dcop — session deltas "
                    "update externals only (structure changes need a "
                    "new session, docs/serving.md)"
                )
            sess.ext_values.update(req.set_values)
            sess.record_delta(req.set_values)
        module = _load_module(req.algo)
        if hasattr(module, "solve_host"):
            return self._solve_session_exact(req, sess, module)
        t_compile0 = time.perf_counter()
        req.dispatch_t = t_compile0
        problem, _fp = sess.compiler.compile({}, sess.ext_values)
        if problem is None:
            raise ServiceError(
                "session dcop has no live variables to solve"
            )
        sess.segments += 1
        run_timeout = None
        if req.timeout is not None:
            run_timeout = max(
                req.timeout - (time.perf_counter() - req.enqueue_t),
                0.01,
            )
        self._record_dispatch(1, 0)
        t_run0 = time.perf_counter()
        req.compile_s = t_run0 - t_compile0
        with trace_scope([req.trace_id]):
            with tr.span(
                "service.dispatch", cat="service", instances=1,
                padded=0, algo=req.algo, session=req.session,
                segment=sess.segments,
            ):
                result = run_batched(
                    problem,
                    module,
                    req.params,
                    rounds=req.rounds,
                    seed=req.seed,
                    timeout=run_timeout,
                    chunk_size=req.chunk_size,
                    convergence_chunks=req.convergence_chunks,
                    n_restarts=req.n_restarts,
                )
        t_done = time.perf_counter()
        req.device_s = t_done - t_run0
        req.decode_t0 = t_done
        out = _result_dict(result)
        out["session"] = req.session
        out["segment"] = sess.segments
        return out

    def _solve_session_exact(
        self, req: _Request, sess: _Session, module
    ) -> Dict[str, Any]:
        """Session dispatch for EXACT algorithms (the ``solve_host``
        modules).  DPOP follow-ups run through the memoized
        contraction session (``engine/memo.py``): ``set_values``
        re-tabulates only the touched constraints and the UTIL sweep
        re-contracts only the dirty root-to-changed-constraint path —
        every other node is a memo hit, and warm deltas perform zero
        XLA compiles (docs/performance.md, "O(delta) re-solves").
        Other exact algos re-solve a pinned private clone.  The
        IncrementalCompiler device-table path is bypassed: exact
        sweeps consume host tables, which the exact session
        re-tabulates itself."""
        tr = get_tracer()
        t_compile0 = time.perf_counter()
        req.dispatch_t = t_compile0
        es = sess.exact.get(req.algo)
        if es is None:
            if req.algo == "dpop":
                from pydcop_tpu.engine.memo import ExactSession

                es = ExactSession(
                    sess.dcop,
                    pad_policy=self.pad_policy,
                    memo_bytes=self.session_memo_bytes,
                )
            else:
                es = _PlainExactSession(sess.dcop, module)
            sess.exact[req.algo] = es
        if sess.ext_values:
            es.set_values(sess.ext_values)
        try:
            sess.exact_params[req.algo] = json.loads(
                json.dumps(dict(req.params))
            )
        except (TypeError, ValueError):
            # non-JSON params: the session still serves, it just
            # cannot warm-replay through a checkpoint
            sess.exact_params.pop(req.algo, None)
        sess.segments += 1
        run_timeout = None
        if req.timeout is not None:
            run_timeout = max(
                req.timeout
                - (time.perf_counter() - req.enqueue_t),
                0.01,
            )
        self._record_dispatch(1, 0)
        t_run0 = time.perf_counter()
        req.compile_s = t_run0 - t_compile0
        with trace_scope([req.trace_id]):
            with tr.span(
                "service.dispatch", cat="service", instances=1,
                padded=0, algo=req.algo, session=req.session,
                segment=sess.segments,
            ):
                out = es.solve(req.params, timeout=run_timeout)
        t_done = time.perf_counter()
        req.device_s = t_done - t_run0
        req.decode_t0 = t_done
        out["session"] = req.session
        out["segment"] = sess.segments
        return out


class _PlainExactSession:
    """Pinned session state for exact algorithms WITHOUT a memoized
    sweep (syncbb): a private dcop clone whose externals follow the
    session's ``set_values`` stream; every solve is a full
    ``solve_host``."""

    def __init__(self, dcop, module) -> None:
        from pydcop_tpu.engine.memo import _clone_dcop

        self.module = module
        self.dcop = _clone_dcop(dcop)

    def set_values(self, values: Mapping[str, Any]) -> None:
        evs = self.dcop.external_variables
        for name, val in values.items():
            ev = evs.get(name)
            if ev is not None and ev.value != val:
                ev.value = val

    def solve(
        self,
        params: Optional[Mapping[str, Any]] = None,
        timeout: Optional[float] = None,
    ) -> Dict[str, Any]:
        return self.module.solve_host(
            self.dcop, dict(params or {}), timeout=timeout
        )


def _load_module(algo_name: str):
    from pydcop_tpu.algorithms import load_algorithm_module

    return load_algorithm_module(algo_name)


# ---------------------------------------------------------------------------
# wire protocol: newline-JSON frames (the hostnet control-plane framing)
# ---------------------------------------------------------------------------
#
# request:  {"op": "solve", "id": N, "cid": client-id, "ikey":
#            idempotency-key, "algo": ..., "dcop": yaml-text | path,
#            "params": {...}, "rounds": ..., "seed": ...,
#            "session": ..., "set_values": {...}, ...}
#           {"op": "stats" | "ping" | "close_session" | "shutdown",
#            "id": N, ...}
# response: {"id": N, "ok": true, "result"|"stats"|...: ...}
#           {"id": N, "ok": false, "error": "..."}
#
# Solve frames PIPELINE: the server admits them asynchronously and
# replies (tagged with the request id) as results land, up to a
# per-connection in-flight cap — the wire-level backpressure knob;
# frames beyond the cap are answered immediately with
# status="shed".  A malformed or oversized frame gets a structured
# error reply and the connection STAYS OPEN (framing is
# newline-delimited, so the stream resyncs at the next newline).
#
# Idempotent retries: each solve frame carries an idempotency key
# ("ikey", stable across resends of the same logical request); the
# server remembers its last replies in a bounded cache, so a client
# that lost a connection AFTER the result was computed (the
# conn_drop chaos kind, or a real peer death) reconnects, resends,
# and is answered from the cache — never re-solved.

_SOLVE_FIELDS = (
    "rounds", "seed", "chunk_size", "convergence_chunks",
    "n_restarts", "timeout", "session", "set_values",
    "max_util_bytes", "bnb", "table_dtype", "table_format",
)

#: fields an ``op: "infer"`` frame may carry — mirrors
#: :meth:`SolverService.submit_infer` (the query itself rides the
#: frame's ``query`` field and joins the dispatch partition key)
_INFER_FIELDS = (
    "order", "beta", "tol", "device", "device_min_cells",
    "timeout", "map_vars", "external_dists", "max_util_bytes",
    "bnb", "table_dtype", "table_format",
)

#: results are trimmed for the wire: the per-round cost trace can be
#: orders of magnitude bigger than the answer
_WIRE_DROP = ("cost_trace", "restart_costs")

#: inbound frame size cap: big enough for any realistic shipped yaml,
#: small enough that one hostile (or corrupted) line cannot balloon a
#: handler's memory — oversized frames get a structured error reply
_MAX_FRAME_BYTES = 32 * 1024 * 1024

#: how long a reply send may block on a slow peer before the
#: connection is declared dead (send-only: receive stays unbounded so
#: idle keep-alive connections survive)
_SEND_TIMEOUT_S = 30


def _read_frame(reader):
    """One inbound frame from a buffered reader.

    Returns ``(msg, None)`` for a valid frame, ``(None, None)`` when
    the peer closed, and ``(None, error_text)`` for a malformed or
    oversized frame — the connection stays usable (newline framing
    resyncs at the next newline; oversized lines are drained first),
    so one bad frame costs its sender an error reply, not the
    connection and every pipelined request behind it."""
    try:
        line = reader.readline(_MAX_FRAME_BYTES + 1)
    except (OSError, ValueError):
        return None, None
    if not line:
        return None, None
    if not line.endswith(b"\n"):
        if len(line) > _MAX_FRAME_BYTES:
            # drain the rest of the oversized line so the stream
            # resyncs on the next newline
            while True:
                try:
                    chunk = reader.readline(_MAX_FRAME_BYTES)
                except (OSError, ValueError):
                    return None, None
                if not chunk or chunk.endswith(b"\n"):
                    break
            return None, (
                f"frame exceeds {_MAX_FRAME_BYTES} bytes"
            )
        return None, None  # EOF mid-frame: treat as closed
    try:
        msg = json.loads(line.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        return None, f"malformed frame: {e}"
    if not isinstance(msg, dict):
        return None, "malformed frame: not a JSON object"
    return msg, None


def _corrupt_payload(payload: bytes) -> bytes:
    """The frame_corrupt chaos kind: invalid-UTF-8 garbage in place of
    the payload's head, framing (trailing newline, length) preserved —
    the receiving side's frame validation must reject it cleanly."""
    return b"\xff\xfe\xfd\xfc" + payload[4:]


#: outbound reply queue bound per connection: a peer that stops
#: reading while this many computed replies pile up is declared dead
#: (its results remain in the reply cache for a reconnect)
_MAX_QUEUED_REPLIES = 64


class _ConnState:
    """Per-connection server bookkeeping: the socket, the client's
    declared id (``cid``), the chaos scope (fresh per connection so
    retries re-roll their fault hashes), the per-connection reply
    sequence the wire fault decisions key on, the in-flight request
    count the backpressure cap reads, and the outbound reply queue a
    dedicated per-connection writer thread drains — result delivery
    (which runs on the tick worker) only ever ENQUEUES, so one
    stalled peer can never block dispatch for everyone else."""

    __slots__ = (
        "sock", "cid", "scope", "reply_seq", "flushed", "inflight",
        "lock", "cond", "outq", "alive",
    )

    def __init__(self, sock: socket.socket, scope: str) -> None:
        self.sock = sock
        self.cid: Optional[str] = None
        self.scope = scope
        self.reply_seq = 0
        self.flushed = 0  # highest reply seq actually sent
        self.inflight = 0
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.outq: deque = deque()
        self.alive = True

    def wait_flushed(self, timeout: float) -> None:
        """Block until every queued reply has been SENT (not just
        popped) or the connection dies — the ordering guarantee
        behind shutdown replies and handler teardown."""
        deadline = time.monotonic() + timeout
        with self.cond:
            while self.alive and (
                self.outq or self.flushed < self.reply_seq
            ):
                left = deadline - time.monotonic()
                if left <= 0:
                    return
                self.cond.wait(left)


class ServiceServer:
    """TCP front for a :class:`SolverService`: accepts connections,
    one handler thread per connection, newline-JSON frames.

    Hardened for the real world (docs/serving.md):

    - solve frames pipeline up to ``max_inflight`` per connection;
      frames beyond the cap are answered ``status="shed"`` instead of
      queueing unboundedly behind one socket;
    - malformed / oversized frames get a structured error reply and
      the connection survives;
    - solve replies are cached by idempotency key (bounded LRU,
      ``reply_cache`` entries), so a retry of a dropped-but-computed
      response replays the answer instead of re-solving;
    - the service's seeded :class:`~pydcop_tpu.faults.plan.FaultPlan`
      wire kinds (``conn_drop``, ``slow_client``, ``frame_corrupt``)
      inject HERE, in the reply path — the serving loop's chaos seam.
    """

    def __init__(
        self,
        service: SolverService,
        host: str = "127.0.0.1",
        port: int = 0,
        max_inflight: int = 8,
        reply_cache: int = 512,
    ):
        if max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1, got {max_inflight}"
            )
        self.service = service
        self.max_inflight = max_inflight
        plan = service.chaos_plan
        self._plan = (
            plan
            if plan is not None and plan.wire_faults_configured
            else None
        )
        self._server = socket.create_server((host, port))
        self.address: Tuple[str, int] = (
            host, self._server.getsockname()[1]
        )
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._states: List[_ConnState] = []  # for close-time flush
        self._lock = threading.Lock()
        self._conn_counter = 0
        # per-client-id connection ordinals (chaos scope freshness on
        # reconnect): bounded LRU like the reply cache — default
        # client ids are unique per client instance, so a resident
        # server would otherwise grow one entry per client EVER
        # served (an evicted cid that returns restarts at ordinal 1,
        # which only re-bases its fault hashes)
        self._cid_conns: "OrderedDict[str, int]" = OrderedDict()
        self._cid_conns_max = 4096
        self._replies: "OrderedDict[str, Dict[str, Any]]" = (
            OrderedDict()
        )
        self._reply_cache_max = reply_cache
        # idempotency keys whose solve is STILL RUNNING: a retry that
        # arrives before the reply cache is populated (client-side
        # timeout shorter than the solve, a slow_client hold) attaches
        # to the in-flight PendingResult instead of submitting a
        # duplicate solve — "never re-solved" covers the in-flight
        # window, not just completed replies
        self._inflight_ikeys: Dict[str, PendingResult] = {}
        # serializes SESSION-frame admission against the `replicate`
        # op: a primary's delta-log stream and the router's failover
        # re-forward arrive on two independent connections, so without
        # a common lock the re-forward's reply-cache check can run
        # BEFORE the replicated {entry + piggybacked reply} applies
        # while its submit runs AFTER — promoting the fresh standby
        # copy and re-executing an already-answered segment.  Held
        # around {apply entry + cache insert} on one side and
        # {cache re-check + submit} on the other; stateless frames
        # never take it.
        self._replica_admission = threading.Lock()
        self._accept = threading.Thread(
            target=self._accept_loop, name="solver-service-accept",
            daemon=True,
        )
        self._accept.start()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until :meth:`close` / a ``shutdown`` op / a SIGTERM
        relayed through :meth:`request_shutdown` (or the timeout);
        returns True when shut down."""
        return self._shutdown.wait(timeout)

    def inflight(self) -> int:
        """Wire-level in-flight request count across all connections
        (the ``/healthz`` ``inflight`` field)."""
        with self._lock:
            states = list(self._states)
        total = 0
        for st in states:
            with st.lock:
                total += st.inflight
        return total

    def request_shutdown(self) -> None:
        """Ask the serve loop to stop (signal-handler safe: only sets
        an event — the thread blocked in :meth:`wait` does the actual
        teardown, so the graceful drain never runs inside a signal
        handler)."""
        self._shutdown.set()

    def close(self) -> None:
        self._shutdown.set()
        try:
            self._server.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
            states = list(self._states)
            threads = list(self._threads)
        # flush before destroying: replies computed during a graceful
        # drain sit in per-connection writer queues — tearing the
        # sockets down first would silently drop them, breaking the
        # "finish and deliver" drain contract.  One shared deadline
        # bounds the whole flush (a genuinely stalled peer forfeits
        # its tail, as always).
        deadline = time.monotonic() + 5.0
        for st in states:
            st.wait_flushed(max(0.0, deadline - time.monotonic()))
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in threads:
            t.join(timeout=5)

    def __enter__(self) -> "ServiceServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _addr = self._server.accept()
            except OSError:
                return  # closed
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(
                target=self._handle, args=(conn,),
                name="solver-service-conn", daemon=True,
            )
            with self._lock:
                self._threads.append(t)
            t.start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            # send-only timeout: the writer must not park forever on
            # a dead peer the TCP stack hasn't noticed; receives stay
            # unbounded so idle keep-alive connections survive
            import struct

            conn.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                struct.pack("ll", _SEND_TIMEOUT_S, 0),
            )
        except (OSError, AttributeError):
            pass
        with self._lock:
            self._conn_counter += 1
            idx = self._conn_counter
        st = _ConnState(conn, scope=f"conn{idx}")
        with self._lock:
            self._states.append(st)
        writer = threading.Thread(
            target=self._writer_loop, args=(st,),
            name="solver-service-writer", daemon=True,
        )
        writer.start()
        reader = conn.makefile("rb")
        try:
            while not self._shutdown.is_set():
                msg, err = _read_frame(reader)
                if msg is None and err is None:
                    return  # peer closed
                if err is not None:
                    self.service.note_frame_rejected()
                    if not self._reply(
                        st,
                        {
                            "id": None,
                            "ok": False,
                            "error": err,
                            "frame_rejected": True,
                        },
                    ):
                        return
                    continue
                cid = msg.get("cid")
                if cid is not None and st.cid is None:
                    st.cid = str(cid)
                    with self._lock:
                        k = self._cid_conns.get(st.cid, 0) + 1
                        self._cid_conns[st.cid] = k
                        self._cid_conns.move_to_end(st.cid)
                        while (
                            len(self._cid_conns)
                            > self._cid_conns_max
                        ):
                            self._cid_conns.popitem(last=False)
                    # scope = (client id, connection ordinal): fault
                    # hashes re-roll on reconnect, replay identically
                    # for the same seed + client behavior
                    st.scope = f"{st.cid}/{k}"
                if msg.get("op") in ("solve", "infer"):
                    self._handle_solve(st, msg)
                    continue
                rid = msg.get("id")
                ikey = msg.get("ikey")
                cached = None
                if ikey is not None:
                    with self._lock:
                        cached = self._replies.get(ikey)
                        if cached is not None:
                            self._replies.move_to_end(ikey)
                if cached is not None:
                    # a retried non-solve op whose ack was lost: the
                    # op already EXECUTED (e.g. close_session popped
                    # its session) — replay the original reply
                    # instead of re-executing and reporting a
                    # different answer
                    self.service.note_replayed_reply()
                    reply = dict(cached)
                else:
                    try:
                        reply = self._serve_op(msg)
                    except Exception as e:  # noqa: BLE001
                        reply = {
                            "ok": False,
                            "error": f"{type(e).__name__}: {e}",
                        }
                    if ikey is not None:
                        self._cache_reply(ikey, reply)
                reply["id"] = rid
                if not self._reply(st, reply):
                    return
                if msg.get("op") == "shutdown":
                    # flush the acknowledgement BEFORE flipping the
                    # shutdown event: teardown races the writer
                    # otherwise and the client would see a reset from
                    # a shutdown that succeeded
                    st.wait_flushed(5.0)
                    self._shutdown.set()
                    return
        finally:
            # give the writer a bounded window to flush what is
            # already queued (a final reply, an error), then signal
            # it down; on an idle close this is instant
            st.wait_flushed(5.0)
            with st.cond:
                st.alive = False
                st.cond.notify_all()
            writer.join(timeout=_SEND_TIMEOUT_S + 1)
            try:
                reader.close()
                conn.close()
            except OSError:
                pass
            # "concurrency is connections" means a resident server
            # sees millions of short-lived ones: drop this handler's
            # bookkeeping or _conns/_threads grow without bound
            with self._lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass
                try:
                    self._states.remove(st)
                except ValueError:
                    pass
                try:
                    self._threads.remove(threading.current_thread())
                except ValueError:
                    pass

    # -- the reply path (where wire chaos injects) -----------------------

    def _reply(self, st: _ConnState, obj: Dict[str, Any]) -> bool:
        """Enqueue one reply frame for the connection's writer thread;
        returns False when the connection is gone.  Never blocks on
        the peer: result delivery runs on the tick worker, and a
        stalled client must cost only ITS connection, not a tick.  A
        peer that stops reading while the bounded queue fills is
        declared dead (the computed results stay in the reply cache
        for its reconnect).  Serialization happens on the WRITER
        thread too — a tick of large results must not pay
        ``json.dumps`` serially on the dispatch hot path."""
        overflow = False
        with st.cond:
            if not st.alive:
                return False
            if len(st.outq) >= _MAX_QUEUED_REPLIES:
                overflow = True
            else:
                st.outq.append(obj)
                st.cond.notify_all()
        if overflow:
            # actually disconnect the stalled peer (outside the cond:
            # _drop_conn re-acquires it) — just marking it dead would
            # leave the handler parked in readline forever and the
            # peer never learning it was dropped
            self._drop_conn(st)
            return False
        return True

    def _writer_loop(self, st: _ConnState) -> None:
        """Drain one connection's reply queue onto its socket.  The
        seeded wire fault kinds act here, in dequeue order (single
        writer, so the per-connection reply sequence the decisions
        key on is deterministic): ``slow_client`` holds the frame,
        ``frame_corrupt`` mangles its bytes (framing intact),
        ``conn_drop`` closes the socket instead of sending — the
        computed result stays in the reply cache for the client's
        idempotent retry."""
        plan = self._plan
        while True:
            with st.cond:
                while st.alive and not st.outq:
                    st.cond.wait()
                if not st.outq:
                    return  # closed and drained
                obj = st.outq.popleft()
                st.reply_seq += 1
                seq = st.reply_seq
                # a closing handler may be waiting for the drain
                st.cond.notify_all()
            payload = (json.dumps(obj) + "\n").encode("utf-8")
            if plan is not None:
                w = plan.wire
                if w.slow_client:
                    self._fault("slow_client", st.scope, seq, plan)
                    time.sleep(w.slow_client)
                if plan.decide_conn_drop(st.scope, seq):
                    self._fault("conn_drop", st.scope, seq, plan)
                    self._drop_conn(st)
                    return
                if plan.decide_frame_corrupt(st.scope, seq):
                    self._fault("frame_corrupt", st.scope, seq, plan)
                    payload = _corrupt_payload(payload)
            try:
                st.sock.sendall(payload)
            except (OSError, ValueError):
                with st.cond:
                    st.alive = False
                    st.cond.notify_all()
                return
            with st.cond:
                st.flushed = seq
                st.cond.notify_all()

    @staticmethod
    def _drop_conn(st: _ConnState) -> None:
        with st.cond:
            st.alive = False
            st.cond.notify_all()  # wake the writer so it exits
        try:
            # shutdown, not just close: the handler's makefile reader
            # still references the fd, so close() alone would leave
            # the wire open and neither end would ever see the drop
            st.sock.shutdown(socket.SHUT_RDWR)
            st.sock.close()
        except OSError:
            pass

    @staticmethod
    def _fault(kind: str, scope: str, seq: int, plan) -> None:
        met = get_metrics()
        tr = get_tracer()
        if met.enabled:
            met.inc(f"fault.{kind}")
        if tr.enabled:
            # conn=, not link=: trace-summary derives per-AGENT rows
            # from `link` args, and a connection scope is not an
            # agent — thousands of reconnecting clients would
            # otherwise drown the agent table in fake rows
            tr.event(
                kind, cat="fault", conn=scope, seq=seq,
                seed=plan.seed,
            )

    # -- ops -------------------------------------------------------------

    def _cache_reply(self, ikey: str, reply: Dict[str, Any]) -> None:
        """The one bounded-LRU reply-cache insert (solve and non-solve
        paths share it, so idempotency semantics cannot drift between
        them)."""
        with self._lock:
            self._replies[ikey] = dict(reply)
            self._replies.move_to_end(ikey)
            while len(self._replies) > self._reply_cache_max:
                self._replies.popitem(last=False)

    def _note_replay(self, msg: Dict[str, Any]) -> None:
        """One replayed reply: count it, and put a trace-tagged event
        on the timeline so `trace-summary --requests` stitches the
        retry attempt back to the ORIGINAL server spans instead of
        showing a gap (or inventing a phantom re-solve)."""
        self.service.note_replayed_reply()
        tr = get_tracer()
        if tr.enabled:
            wt = parse_wire_trace(msg.get("trace"))
            tr.event(
                "service-replay", cat="service",
                trace=wt[0] if wt else None,
                attempt=wt[2] if wt else None,
            )

    def _handle_solve(self, st: _ConnState, msg: Dict[str, Any]) -> None:
        rid = msg.get("id")
        ikey = msg.get("ikey")
        cached = None
        pending: Optional[PendingResult] = None
        if ikey is not None:
            # ONE lock acquisition over both lookups: deliver's
            # critical section (cache insert + in-flight unregister)
            # is atomic under the same lock, so a retry racing the
            # original's completion hits exactly one of the two —
            # checking them separately would leave a window where it
            # misses both and re-solves
            with self._lock:
                cached = self._replies.get(ikey)
                if cached is not None:
                    self._replies.move_to_end(ikey)
                else:
                    pending = self._inflight_ikeys.get(ikey)
        if cached is not None:
            # a retry of a computed-but-lost response: answer from
            # the bounded reply cache, never re-solve
            self._note_replay(msg)
            self._reply(st, {**cached, "id": rid})
            return
        with st.lock:
            over_cap = st.inflight >= self.max_inflight
            if not over_cap:
                st.inflight += 1
        if over_cap:
            # per-connection backpressure: a pipelining client past
            # its cap is shed immediately, not queued unboundedly
            self.service.note_shed("inflight-cap")
            self._reply(
                st,
                {
                    "id": rid,
                    "ok": True,
                    "result": {
                        "status": "shed",
                        # machine-readable token, like queue-full /
                        # deadline — clients dispatch on it
                        "shed_reason": "inflight-cap",
                        "max_inflight": self.max_inflight,
                    },
                },
            )
            return
        # a retry racing its original: the first frame's solve is
        # still in flight — attach this connection to it instead of
        # submitting a duplicate ("never re-solved" must cover the
        # in-flight window too, or a client timeout shorter than the
        # solve would burn a dispatch slot per retry and re-apply
        # session deltas)
        if pending is not None:
            self._note_replay(msg)
        else:
            pending = self._admit_and_submit(st, msg, rid, ikey)
            if pending is None:
                # already replied (a replicated-reply replay or a
                # validation error)
                return

        def deliver(p: PendingResult) -> None:
            with st.lock:
                st.inflight -= 1
            t_del0 = time.perf_counter()
            try:
                result = p.result(0)
                reply = {
                    "ok": True,
                    "result": {
                        k: v
                        for k, v in result.items()
                        if k not in _WIRE_DROP
                    },
                }
                # the last phase segment: terminal result -> reply
                # handed to the connection writer.  Serialization and
                # the socket send run after this frame leaves the
                # server's attribution window — the remaining gap in
                # a client-measured latency is wire time.
                phases = reply["result"].get("phases")
                if isinstance(phases, dict):
                    phases["reply_write"] = round(
                        time.perf_counter() - t_del0, 6
                    )
            except Exception as e:  # noqa: BLE001 — the error IS
                # the reply
                reply = {
                    "ok": False,
                    "error": f"{type(e).__name__}: {e}",
                }
            if ikey is not None:
                self._cache_reply(ikey, reply)
                with self._lock:
                    # cache first, THEN unregister: a retry arriving
                    # in between sees the cached reply
                    if self._inflight_ikeys.get(ikey) is p:
                        del self._inflight_ikeys[ikey]
            session = msg.get("session")
            if (
                session is not None
                and reply.get("ok")
                and (reply.get("result") or {}).get("status")
                != "shed"
            ):
                # replicate BEFORE the reply leaves: once the client
                # can observe this answer, the session state behind
                # it — and the cached reply a failover retry will ask
                # for — already lives on the standby chain
                self.service.replicate_session(
                    str(session),
                    cache=(
                        {"ikey": ikey, "reply": reply}
                        if ikey is not None
                        else None
                    ),
                )
            self._reply(st, {**reply, "id": rid})

        pending.add_done_callback(deliver)

    def _admit_and_submit(
        self,
        st: _ConnState,
        msg: Dict[str, Any],
        rid: Any,
        ikey: Optional[str],
    ) -> Optional[PendingResult]:
        """Admission past the caches: register the in-flight
        placeholder and submit.  Returns the PendingResult to deliver
        from, or None when this frame was already replied to here.

        Session frames run under ``_replica_admission``, serialized
        against the ``replicate`` op: a primary's final delta-log
        frame and the router's failover re-forward of the request
        that produced it arrive on two independent connections, so
        the piggybacked reply can land between `_handle_solve`'s
        first cache check and the submit — the re-check under the
        SHARED lock either replays it or commits to executing first
        (in which case the late entry parks as an inert standby copy
        and the identical piggybacked reply overwrites nothing)."""
        admission = (
            self._replica_admission
            if msg.get("session") is not None
            else None
        )
        if admission is not None:
            admission.acquire()
        try:
            if admission is not None and ikey is not None:
                with self._lock:
                    cached = self._replies.get(ikey)
                    if cached is not None:
                        self._replies.move_to_end(ikey)
                if cached is not None:
                    with st.lock:
                        st.inflight -= 1
                    self._note_replay(msg)
                    self._reply(st, {**cached, "id": rid})
                    return None
            placeholder: Optional[PendingResult] = None
            pending: Optional[PendingResult] = None
            if ikey is not None:
                # register a placeholder BEFORE submit: admission
                # itself can be slow (parsing a shipped yaml), and a
                # retry landing during it must attach here instead of
                # double-submitting.  The re-check closes the race
                # with another handler doing the same.
                placeholder = PendingResult()
                with self._lock:
                    existing = self._inflight_ikeys.get(ikey)
                    if existing is not None:
                        pending = existing
                        placeholder = None
                    else:
                        self._inflight_ikeys[ikey] = placeholder
            if pending is not None:
                self._note_replay(msg)
                return pending
            try:
                if msg.get("op") == "infer":
                    kwargs = {
                        k: msg[k]
                        for k in _INFER_FIELDS
                        if msg.get(k) is not None
                    }
                    real = self.service.submit_infer(
                        msg.get("dcop"),
                        msg.get("query", "marginals"),
                        trace=msg.get("trace"),
                        **kwargs,
                    )
                else:
                    kwargs = {
                        k: msg[k]
                        for k in _SOLVE_FIELDS
                        if msg.get(k) is not None
                    }
                    real = self.service.submit(
                        msg.get("dcop"),
                        msg.get("algo"),
                        msg.get("params") or None,
                        trace=msg.get("trace"),
                        **kwargs,
                    )
            except Exception as e:  # noqa: BLE001 — per-request
                if placeholder is not None:
                    # resolve attached retries with the SAME
                    # validation error, then unregister (errors
                    # are cheap to recompute, so no cache entry)
                    placeholder._set_error(
                        ServiceError(
                            f"{type(e).__name__}: {e}"
                        )
                    )
                    with self._lock:
                        if (
                            self._inflight_ikeys.get(ikey)
                            is placeholder
                        ):
                            del self._inflight_ikeys[ikey]
                with st.lock:
                    st.inflight -= 1
                self._reply(
                    st,
                    {
                        "id": rid,
                        "ok": False,
                        "error": f"{type(e).__name__}: {e}",
                    },
                )
                return None
            if placeholder is not None:
                # the placeholder IS the canonical in-flight
                # handle: mirror the real result into it
                ph = placeholder

                def _mirror(p: PendingResult) -> None:
                    if p._error is not None:
                        ph._set_error(p._error)
                    else:
                        ph._set_result(p._result)

                real.add_done_callback(_mirror)
                return ph
            return real
        finally:
            if admission is not None:
                admission.release()

    def _serve_op(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        op = msg.get("op")
        if op == "ping":
            return {"ok": True, "pong": True}
        if op == "stats":
            return {"ok": True, "stats": self.service.stats()}
        if op == "close_session":
            name = msg.get("session", "")
            closed = self.service.close_session(name)
            if closed:
                # stream the tombstone so the standby chain drops its
                # copy — a closed session must not fail over
                self.service.replicate_session(str(name))
            return {"ok": True, "closed": closed}
        if op == "replicate":
            entry = msg.get("entry")
            if not isinstance(entry, dict):
                raise ServiceError(
                    "replicate needs entry={session entry} "
                    "(docs/serving.md, 'The fleet')"
                )
            with self._replica_admission:
                # entry + piggybacked reply become visible atomically
                # w.r.t. session admission (_admit_and_submit): a
                # failover re-forward racing this frame either sees
                # both (replays) or neither (executes first)
                info = self.service.apply_replica_entry(entry)
                cache = msg.get("cache")
                if isinstance(cache, dict) and cache.get("ikey"):
                    # the primary's delivered reply rides along: cache
                    # it HERE so a failover retry of an answered
                    # request replays instead of re-solving
                    # (exactly-once)
                    replayed = dict(cache.get("reply") or {})
                    replayed.pop("id", None)
                    self._cache_reply(str(cache["ikey"]), replayed)
            return {"ok": True, "replicated": True, **info}
        if op == "standby":
            addrs = msg.get("standbys")
            if not isinstance(addrs, list) or not all(
                isinstance(a, str) for a in addrs
            ):
                raise ServiceError(
                    "standby needs standbys=[\"host:port\", ...] "
                    "(docs/serving.md, 'The fleet')"
                )
            streamed = self.service.set_standbys(addrs)
            return {
                "ok": True,
                "standbys": list(addrs),
                "streamed": streamed,
            }
        if op == "shutdown":
            return {"ok": True, "stopping": True}
        raise ServiceError(f"unknown op {op!r}")


#: per-process ordinal for default client ids (unique within the
#: process; combined with the pid for cross-process uniqueness)
_CLIENT_ORDINAL = [0]
_CLIENT_ORDINAL_LOCK = threading.Lock()


def _next_client_id() -> str:
    with _CLIENT_ORDINAL_LOCK:
        _CLIENT_ORDINAL[0] += 1
        n = _CLIENT_ORDINAL[0]
    return f"c{os.getpid():x}-{n}"


class ServiceClient:
    """Thin blocking client for a :class:`ServiceServer` (also
    exported as ``pydcop_tpu.api.ServiceClient``).

    One request in flight at a time per client; open more clients for
    concurrency — concurrent clients are exactly what the service
    coalesces.  ``dcop`` arguments that name an existing file are
    read and shipped as yaml text, so the server needs no shared
    filesystem.

    Resilient by default: a dropped connection or a corrupt reply
    frame triggers keyed-backoff reconnect (``utils/backoff.py`` —
    jitter is a pure hash of ``(client id, attempt)``, so chaos
    replays reproduce retry timing) for up to ``retry_window``
    seconds, resending the SAME frame.  Every solve frame carries an
    idempotency key, so a retry of a request whose response was
    computed-but-lost is answered from the server's reply cache,
    never re-solved.  ``retry_window=0`` disables retries (failures
    surface as :class:`ServiceError` immediately).  Retries are
    counted on ``service.client_retries``.
    """

    def __init__(
        self,
        address: Union[str, Tuple[str, int]],
        timeout: Optional[float] = None,
        *,
        client_id: Optional[str] = None,
        retry_window: float = 5.0,
        backoff_seed: int = 0,
    ):
        if isinstance(address, str):
            host, _, port = address.rpartition(":")
            address = (host or "127.0.0.1", int(port))
        self._address: Tuple[str, int] = address
        self._timeout = timeout
        self.client_id = client_id or _next_client_id()
        self.retry_window = retry_window
        self._backoff_seed = backoff_seed
        self._lock = threading.Lock()
        self._next_id = 0
        self._closed = False
        # idempotency keys must be unique per logical request across
        # client LIFETIMES, not just within one: request ids restart
        # at 1 per instance, so a reused client_id (explicit, or a
        # recycled pid in the default) would otherwise collide with a
        # previous life's keys and replay ITS cached replies.  The
        # nonce scopes the keys to this instance; chaos scopes key on
        # client_id alone, so determinism is untouched.
        # graftlint: allow[impure-call] — entropy is the point here
        self._ikey_nonce = os.urandom(4).hex()
        self._sock: Optional[socket.socket] = None
        self._reader = None
        self._connect()  # fail fast on a dead address

    def _connect(self) -> None:
        self._sock = socket.create_connection(
            self._address, timeout=self._timeout
        )
        self._reader = self._sock.makefile("rb")

    def _teardown(self) -> None:
        try:
            if self._reader is not None:
                self._reader.close()
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._reader = None
        self._sock = None

    def _recv_checked(self, reader) -> Dict[str, Any]:
        """One reply frame through the SAME validation the server
        applies inbound (:func:`_read_frame` — one definition of the
        framing rules, not two drifting copies), raising instead of
        returning error tuples: a closed stream raises
        ConnectionError, an oversized / truncated / non-JSON /
        non-object frame raises ValueError.  Both are retryable (the
        frame_corrupt chaos kind lands here)."""
        reply, err = _read_frame(reader)
        if err is not None:
            if err.startswith("frame exceeds"):
                # unlike a chaos-corrupted frame, an OVERSIZED reply
                # is deterministic — every retry replays the same
                # frame from the server's cache — so retrying can
                # only burn the window before failing anyway
                raise ServiceError(f"reply {err}")
            raise ValueError(err)
        if reply is None:
            raise ConnectionError(
                "service connection closed mid-request"
            )
        return reply

    def _attempt(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        if self._closed:
            # close() must WIN against an in-flight call's retry
            # loop — reconnecting a client the user just closed would
            # resurrect the socket for up to retry_window seconds
            raise ServiceError("client is closed")
        if self._sock is None:
            self._connect()
        # locals, not self._sock/_reader: a concurrent close() nulls
        # the attributes mid-attempt, and None.sendall would escape
        # the (OSError, ValueError) retry contract as AttributeError
        # — the closed file objects raise the RIGHT exceptions, and
        # the retry's _closed check then aborts cleanly
        sock, reader = self._sock, self._reader
        try:
            from pydcop_tpu.infrastructure.hostnet import _send

            _send(sock, frame)
            while True:
                reply = self._recv_checked(reader)
                if reply.get("id") == frame["id"]:
                    return reply
                if (
                    reply.get("id") is None
                    and reply.get("frame_rejected")
                ):
                    # the server rejected OUR frame (malformed /
                    # oversized) — with one request in flight per
                    # connection it unambiguously belongs to this
                    # request, and resending the same frame can only
                    # be rejected again: surface, don't retry
                    raise ServiceError(
                        "server rejected the request frame: "
                        f"{reply.get('error')}"
                    )
        except (OSError, ValueError):
            # leave no half-read stream behind: the retry reconnects
            self._teardown()
            raise

    def _call(self, op: str, **fields) -> Dict[str, Any]:
        with self._lock:
            self._next_id += 1
            rid = self._next_id
            frame = {
                "op": op, "id": rid, "cid": self.client_id, **fields,
            }
            tid: Optional[str] = None
            attempts = [0]
            if op not in ("ping", "stats"):
                # stable across resends of this frame — the server's
                # reply-cache dedupe key.  Solves AND state-mutating
                # ops (close_session) need it: a retried
                # close_session whose ack was lost would otherwise
                # re-execute and report closed=False for a session
                # that WAS closed.  ping/stats are read-only and
                # should return fresh data on retry.
                frame["ikey"] = (
                    f"{self.client_id}:{self._ikey_nonce}:{rid}"
                )
                # the request trace id rides next to the idempotency
                # key: stable across resends (so a replayed reply
                # stitches to the ORIGINAL server spans), pure in
                # (client id, request ordinal) so chaos replays
                # produce identical stitched timelines
                # (telemetry/context.py)
                tid = mint_trace_id(self.client_id, rid)

            def _one_attempt() -> Dict[str, Any]:
                if tid is None:
                    return self._attempt(frame)
                attempts[0] += 1
                frame["trace"] = wire_trace(tid, attempts[0])
                tr = get_tracer()
                t0 = time.perf_counter()
                status = "ok"
                try:
                    return self._attempt(frame)
                except BaseException as e:
                    status = type(e).__name__
                    raise
                finally:
                    if tr.enabled:
                        tr.add_span(
                            "client.attempt", "service", t0,
                            time.perf_counter() - t0, trace=tid,
                            span=frame["trace"]["span"],
                            attempt=attempts[0], op=op,
                            status=status,
                        )

            t_req0 = time.perf_counter()
            req_status = "error"
            try:
                if self.retry_window <= 0:
                    try:
                        reply = _one_attempt()
                    except (OSError, ValueError) as e:
                        raise ServiceTransportError(
                            f"service request failed: "
                            f"{type(e).__name__}: {e}"
                        ) from e
                else:
                    from pydcop_tpu.utils.backoff import (
                        call_with_backoff,
                    )

                    met = get_metrics()

                    def _note_retry(
                        attempt: int, error: BaseException
                    ):
                        if met.enabled:
                            met.inc("service.client_retries")

                    try:
                        reply = call_with_backoff(
                            _one_attempt,
                            retry_for=self.retry_window,
                            exceptions=(OSError, ValueError),
                            base=0.05,
                            max_delay=1.0,
                            key=f"service-client/{self.client_id}",
                            seed=self._backoff_seed,
                            on_retry=_note_retry,
                            giving_up=lambda: self._closed,
                        )
                    except (OSError, ValueError) as e:
                        raise ServiceTransportError(
                            f"service request failed after "
                            f"{self.retry_window}s of retries: "
                            f"{type(e).__name__}: {e}"
                        ) from e
                if reply.get("ok"):
                    req_status = str(
                        (reply.get("result") or {}).get(
                            "status", "ok"
                        )
                    )
            finally:
                # the whole-request span: its dur IS the
                # client-measured end-to-end latency the reply's
                # phase breakdown is judged against
                if tid is not None:
                    tr = get_tracer()
                    if tr.enabled:
                        tr.add_span(
                            "client.request", "service", t_req0,
                            time.perf_counter() - t_req0, trace=tid,
                            op=op, attempts=attempts[0],
                            status=req_status,
                        )
        if not reply.get("ok"):
            raise ServiceError(
                reply.get("error", "service request failed")
            )
        return reply

    def forward(
        self, frame: Mapping[str, Any]
    ) -> Dict[str, Any]:
        """Forward a received wire frame downstream — the fleet
        router's primitive (``engine/fleet.py``).  Only the
        wire-local ``id`` is rewritten; the ORIGINAL client's ``cid``,
        idempotency key and trace context ride through untouched, so
        the downstream reply cache dedupes on the END CLIENT's key (a
        failover re-forward of an answered request replays instead of
        re-solving) and the trace stitches across the hop.  Runs the
        same keyed-backoff retry loop as :meth:`_call`; a structured
        ``ok: false`` reply is RETURNED (the router relays it
        verbatim), only transport failure raises
        :class:`ServiceTransportError`."""
        with self._lock:
            self._next_id += 1
            fwd = dict(frame)
            fwd["id"] = self._next_id

            def _one_attempt() -> Dict[str, Any]:
                return self._attempt(fwd)

            if self.retry_window <= 0:
                try:
                    reply = _one_attempt()
                except (OSError, ValueError) as e:
                    raise ServiceTransportError(
                        f"forward failed: {type(e).__name__}: {e}"
                    ) from e
            else:
                from pydcop_tpu.utils.backoff import (
                    call_with_backoff,
                )

                met = get_metrics()

                def _note_retry(attempt: int, error: BaseException):
                    if met.enabled:
                        met.inc("service.client_retries")

                try:
                    reply = call_with_backoff(
                        _one_attempt,
                        retry_for=self.retry_window,
                        exceptions=(OSError, ValueError),
                        base=0.05,
                        max_delay=1.0,
                        key=f"service-client/{self.client_id}",
                        seed=self._backoff_seed,
                        on_retry=_note_retry,
                        giving_up=lambda: self._closed,
                    )
                except (OSError, ValueError) as e:
                    raise ServiceTransportError(
                        f"forward failed after {self.retry_window}s "
                        f"of retries: {type(e).__name__}: {e}"
                    ) from e
        out = dict(reply)
        out.pop("id", None)
        return out

    def solve(
        self,
        dcop: Optional[str] = None,
        algo: Optional[str] = None,
        params: Optional[Mapping[str, Any]] = None,
        **kwargs,
    ) -> Dict[str, Any]:
        """Solve over the wire; kwargs mirror
        :meth:`SolverService.submit` (rounds, seed, chunk_size,
        convergence_chunks, n_restarts, timeout, session,
        set_values)."""
        unknown = set(kwargs) - set(_SOLVE_FIELDS)
        if unknown:
            raise ValueError(
                f"unknown solve field(s) {sorted(unknown)}; the wire "
                f"protocol accepts {_SOLVE_FIELDS}"
            )
        if (
            isinstance(dcop, str)
            and "\n" not in dcop
            and os.path.isfile(dcop)
        ):
            with open(dcop, encoding="utf-8") as f:
                dcop = f.read()
        reply = self._call(
            "solve", dcop=dcop, algo=algo,
            params=dict(params) if params else None, **kwargs,
        )
        return reply["result"]

    def infer(
        self,
        dcop: Optional[str] = None,
        query: str = "marginals",
        **kwargs,
    ) -> Dict[str, Any]:
        """Inference over the wire; kwargs mirror
        :meth:`SolverService.submit_infer` (order, beta, tol, device,
        device_min_cells, timeout, map_vars, external_dists,
        max_util_bytes).  Mixed-query clients coalesce per query in
        the service's ticks."""
        unknown = set(kwargs) - set(_INFER_FIELDS)
        if unknown:
            raise ValueError(
                f"unknown infer field(s) {sorted(unknown)}; the wire "
                f"protocol accepts {_INFER_FIELDS}"
            )
        if (
            isinstance(dcop, str)
            and "\n" not in dcop
            and os.path.isfile(dcop)
        ):
            with open(dcop, encoding="utf-8") as f:
                dcop = f.read()
        reply = self._call(
            "infer", dcop=dcop, query=query, **kwargs,
        )
        return reply["result"]

    def stats(self) -> Dict[str, Any]:
        return self._call("stats")["stats"]

    def ping(self) -> bool:
        return bool(self._call("ping").get("pong"))

    def close_session(self, name: str) -> bool:
        return bool(
            self._call("close_session", session=name).get("closed")
        )

    def shutdown(self) -> None:
        """Ask the server process to stop serving.  Best-effort by
        nature: a transport failure counts as success, because a
        shutdown that WORKED kills the address the retry loop would
        need — the idempotent resend (shutdown frames do carry a key)
        can only bang on a dead listener until the window expires and
        surface a spurious error.  Only a structured server-side
        refusal raises."""
        try:
            self._call("shutdown")
        except ServiceTransportError:
            pass

    def close(self) -> None:
        # flag first, no lock: an in-flight call holds the client
        # lock for its whole request — close() aborts its retry loop
        # via the flag/giving_up instead of waiting it out
        self._closed = True
        self._teardown()

    def __enter__(self) -> "ServiceClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
