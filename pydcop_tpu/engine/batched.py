"""The synchronous-batched TPU engine.

This replaces the reference's thread-per-agent runtime
(``pydcop/infrastructure/agents.py`` + ``communication.py``) for the
solve path: one jitted step = one DCOP round for *every* agent
simultaneously; a ``lax.scan`` over rounds compiles the whole run into
a single XLA program.  Host↔device traffic is one transfer per chunk
(state in, cost trace out), not one queue op per message.

Anytime behavior matches the reference's orchestrator: the engine
tracks the best assignment seen across all rounds and reports both the
final and the best solution, plus the per-round cost trace (the
``collect_on=cycle_change`` metric stream).
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import OrderedDict
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
    Union,
)

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from pydcop_tpu.engine.supervisor import (
    DeviceOOMError,
    DeviceTransientError,
    UnrecoverableDeviceError,
    get_supervisor,
)
from pydcop_tpu.utils.backoff import backoff_delays
from pydcop_tpu.ops.compile import (
    CompiledProblem,
    canonical_execution_problem,
    decode_assignment,
)
from pydcop_tpu.ops.costs import total_cost
from pydcop_tpu.telemetry import get_metrics, get_tracer
from pydcop_tpu.telemetry.jit import profiled_jit


@dataclasses.dataclass
class RunResult:
    """Outcome of a batched run (costs in the problem's native sign)."""

    assignment: Dict[str, Any]  # final assignment
    cost: float  # final cost
    best_assignment: Dict[str, Any]  # best-seen (anytime) assignment
    best_cost: float
    cycles: int  # rounds executed
    messages: int  # logical messages (see algo.messages_per_round)
    time: float  # wall-clock seconds (incl. compile)
    status: str  # 'finished' | 'timeout' | 'converged'
    cost_trace: np.ndarray  # per-round cost (native sign)
    # per-restart best costs (native sign) when n_restarts > 1 — the
    # K-sample distribution behind the reported best (None otherwise)
    restart_costs: Optional[np.ndarray] = None
    # the final algorithm state as host arrays when return_state=True
    # (the dynamic engine's state-transfer carry; None otherwise)
    state: Optional[Dict[str, np.ndarray]] = None


# Compiled chunk runners, reused across run_batched calls so repeated
# runs (warmup/measure, parameter sweeps, chunked loops) don't re-trace.
# Key: (algo module, axis_name, static params, dyn-param names, mesh id,
# bucket arities, n_shards, chunk len).  Unbounded by default: entries
# pin their executable + mesh for the process lifetime, which is the
# desired behavior for benchmark loops; call _RUNNER_CACHE.clear() to
# release, or cap it with :func:`set_runner_cache_limit` (LRU
# eviction, counted as ``engine.runner_cache_evictions``) for
# long-lived processes sweeping many (algo, chunk, shape) combinations.
_RUNNER_CACHE: "OrderedDict[Tuple, Callable]" = OrderedDict()
_RUNNER_CACHE_MAX: Optional[int] = None

# env override for embedders/sweep drivers that never call the setter;
# 0 (and any value <= 0) means unbounded, matching the None default
_env_cap = os.environ.get("PYDCOP_TPU_RUNNER_CACHE_MAX")
if _env_cap:
    try:
        _parsed_cap = int(_env_cap)
    except ValueError:
        import logging

        logging.getLogger(__name__).warning(
            "ignoring non-integer PYDCOP_TPU_RUNNER_CACHE_MAX=%r",
            _env_cap,
        )
    else:
        _RUNNER_CACHE_MAX = _parsed_cap if _parsed_cap > 0 else None


def set_runner_cache_limit(max_entries: Optional[int]) -> None:
    """Cap the chunk-runner cache at ``max_entries`` (LRU eviction;
    ``None`` restores the unbounded default).  Evicts immediately if
    the cache is already over the new cap."""
    global _RUNNER_CACHE_MAX
    if max_entries is not None and max_entries < 1:
        raise ValueError(
            f"max_entries must be >= 1 or None, got {max_entries}"
        )
    _RUNNER_CACHE_MAX = max_entries
    _evict_runners()


def _evict_runners() -> None:
    met = get_metrics()
    while (
        _RUNNER_CACHE_MAX is not None
        and len(_RUNNER_CACHE) > _RUNNER_CACHE_MAX
    ):
        _RUNNER_CACHE.popitem(last=False)
        if met.enabled:
            met.inc("engine.runner_cache_evictions")


def _default_unroll() -> int:
    """Scan unroll for the round loop, by backend: on CPU unrolling 2
    rounds lets XLA fuse across the boundary (measured 2.3x at 10k
    vars, BASELINE.md round 1); on TPU the same unroll is ~25% SLOWER
    (round-3 profile: 606 vs 768 us/round) — the round is launch-bound
    and unrolling just bloats the program."""
    return 1 if jax.default_backend() == "tpu" else 2


def _chunk_runner(
    algo_step: Callable,
    n_rounds: int,
    axis_name: Optional[str] = None,
    cost_every: int = 1,
    cost_fn: Optional[Callable] = None,
) -> Callable:
    """Build the scan over ``n_rounds`` rounds.

    Carry: (state, best_cost, best_values).  Output: cost at every
    ``cost_every``-th round (``ceil(n_rounds / cost_every)`` values).

    ``cost_every > 1`` samples the anytime cost/best tracking instead
    of paying it each round — on TPU the cost evaluation costs as much
    as a whole Max-Sum round (round-3 profile), and the reference
    itself only observes cost at the orchestrator's collection period,
    not per agent cycle.  Per-round RNG streams are unchanged: the key
    for round ``i`` of a chunk is ``fold_in(chunk_key, i)`` regardless
    of the sampling structure.

    ``cost_fn(problem, values)`` overrides the cost evaluation (the
    multi-restart engine passes a vmapped one; ``best_cost`` is then
    per-restart ``[R]`` and ``best_values`` ``[R, n]`` — the selection
    below broadcasts over both layouts).
    """
    unroll = _default_unroll()
    if cost_fn is None:
        def cost_fn(problem, values):
            return total_cost(problem, values, axis_name)

    def _track_best(problem, state, best_cost, best_values):
        values = state["values"]
        cost = cost_fn(problem, values)
        better = cost < best_cost
        best_cost = jnp.where(better, cost, best_cost)
        # scalar: better[..., None] is [1], broadcasts over [n];
        # per-restart: [R, 1] over [R, n]
        best_values = jnp.where(better[..., None], values, best_values)
        return best_cost, best_values, cost

    def run_chunk(problem, state, key, params, best_cost, best_values):
        def rounds_span(state, start, count):
            """``count`` algorithm rounds, no cost evaluation."""

            def round_fn(s, i):
                return algo_step(
                    problem, s, jax.random.fold_in(key, i), params
                ), ()

            if count == 1:
                s, _ = round_fn(state, start)
                return s
            state, _ = jax.lax.scan(
                round_fn,
                state,
                start + jnp.arange(count),
                unroll=unroll if count % unroll == 0 else 1,
            )
            return state

        def sample_fn(carry, j):
            state, best_cost, best_values = carry
            state = rounds_span(state, j * cost_every, cost_every)
            best_cost, best_values, cost = _track_best(
                problem, state, best_cost, best_values
            )
            return (state, best_cost, best_values), cost

        n_outer, rem = divmod(n_rounds, cost_every)
        carry = (state, best_cost, best_values)
        costs_parts = []
        if n_outer:
            carry, costs = jax.lax.scan(
                sample_fn,
                carry,
                jnp.arange(n_outer),
                # with cost_every == 1 the sample loop IS the round
                # loop — keep the cross-round unroll fusion there
                unroll=(
                    unroll
                    if cost_every == 1 and n_outer % unroll == 0
                    else 1
                ),
            )
            costs_parts.append(costs)
        if rem:  # tail rounds of a chunk not divisible by cost_every
            state, best_cost, best_values = carry
            state = rounds_span(state, n_outer * cost_every, rem)
            best_cost, best_values, cost = _track_best(
                problem, state, best_cost, best_values
            )
            carry = (state, best_cost, best_values)
            costs_parts.append(cost[None])
        state, best_cost, best_values = carry
        costs = (
            jnp.concatenate(costs_parts)
            if len(costs_parts) > 1
            else costs_parts[0]
        )
        return state, best_cost, best_values, costs

    return run_chunk


def _build_step(algo_module, step_statics, axis_name, n_restarts):
    """Build the per-round ``(algo_step, cost_fn)`` pair for ONE
    problem instance: the step closure the chunk runner scans, with
    the restart ``vmap`` applied when ``n_restarts > 1`` (``cost_fn``
    then evaluates the ``[R, n]`` restart stack and returns ``[R]``).

    Shared by :func:`run_batched` and :func:`run_many_batched` — the
    latter vmaps this pair once more over the instance axis, so the
    two vmaps compose orthogonally as ``[instance, restart, ...]``.
    Under a mesh the returned step runs INSIDE ``shard_map``: the
    step's psum still reduces over the shard axis per restart (vmap
    and the named axis are orthogonal).
    """
    if n_restarts > 1:
        restart_ids = jnp.arange(n_restarts)

        def algo_step(problem, state, key, dyn):
            keys = jax.vmap(
                lambda i: jax.random.fold_in(key, i)
            )(restart_ids)
            return jax.vmap(
                lambda s, k: algo_module.step(
                    problem, s, k, {**step_statics, **dyn},
                    axis_name=axis_name,
                ),
                in_axes=(0, 0),
            )(state, keys)

        def cost_fn(problem, values):
            return jax.vmap(
                lambda v: total_cost(problem, v, axis_name)
            )(values)

        return algo_step, cost_fn

    def algo_step(problem, state, key, dyn):
        return algo_module.step(
            problem, state, key, {**step_statics, **dyn},
            axis_name=axis_name,
        )

    return algo_step, None


def run_batched(
    problem: CompiledProblem,
    algo_module,
    params: Dict[str, Any],
    rounds: int = 100,
    seed: int = 0,
    timeout: Optional[float] = None,
    chunk_size: int = 64,
    convergence_chunks: int = 0,
    mesh=None,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 1,
    resume: bool = False,
    chunk_callback: Optional[Callable[[int, float], Optional[str]]] = None,
    cost_every: int = 1,
    n_restarts: int = 1,
    initial_state: Optional[Dict[str, Any]] = None,
    return_state: bool = False,
) -> RunResult:
    """Run a batched algorithm for up to ``rounds`` rounds.

    The run proceeds in jit-compiled chunks of ``chunk_size`` rounds;
    between chunks the host checks ``timeout`` and (optionally)
    convergence: if ``convergence_chunks > 0`` and neither the best cost
    improved nor any value changed for that many consecutive chunks, the
    run stops with status ``converged``.

    Non-numeric params (e.g. DSA's ``variant``) are baked into the
    compiled step — they must be hashable.  Numeric params are passed as
    arrays so parameter sweeps don't recompile.

    With ``mesh`` set (a 1-D ``jax.sharding.Mesh``), the whole chunk
    runs under ``shard_map``: constraint/edge arrays and message state
    are sharded over the mesh, variables replicated, neighbor exchange
    via ``psum`` (see ``pydcop_tpu.parallel``).  The problem must have
    been compiled with ``n_shards == mesh size``.

    With ``checkpoint_path`` set, the run state is written every
    ``checkpoint_every`` chunks (atomic .npz, see
    ``engine.checkpoint``); ``resume=True`` restores it and continues
    from the recorded round counter.

    ``cost_every`` samples the anytime cost/best-assignment tracking
    every that many rounds instead of every round (the cost evaluation
    is as expensive as a whole Max-Sum round on TPU); the cost trace
    then has one entry per sample.  Algorithm semantics and RNG
    streams are unaffected.

    ``chunk_callback(done_rounds, best_cost)`` is invoked at every
    *interior* chunk boundary (``done < rounds``), before the local
    timeout/convergence checks.  Returning a status string stops the
    run with that status; returning ``None`` continues.  The
    cross-process orchestrator uses this as its lockstep control point
    so every ``jax.distributed`` process stops at the same boundary
    (a wall-clock check per process would diverge).

    ``n_restarts > 1`` runs that many INDEPENDENT solver instances
    (distinct RNG streams, same problem) inside every jitted step via
    ``vmap`` and reports the best across them — batched parallel
    restarts.  This is the reference's "run the stochastic algorithm K
    times, keep the best" experiment loop collapsed into one device
    program: on accelerators small problems are launch-bound, so K
    restarts cost barely more wall-clock than one.  The cost trace
    carries the per-sample minimum across restarts; ``msg_count``
    counts all restarts' messages (K independent runs);
    ``convergence_chunks`` judges the across-restart BEST cost only
    (requiring all K instances to freeze would disable early stop).
    Restarts COMPOSE with ``mesh`` (the vmap runs inside
    ``shard_map``: per restart, edges stay sharded and the neighbor
    exchange still rides one psum) and with checkpoint/resume (the
    whole [K, ...] restart stack round-trips; ``n_restarts`` is
    validated against the checkpoint).  Only ``wants_values`` chunk
    callbacks (the elastic runtime) remain incompatible.

    ``initial_state`` seeds the run with a previous run's full state
    pytree (same problem structure and, for restarts, same K) instead
    of ``init_state`` — the dynamic engine's state transfer: Max-Sum
    messages / DBA weights survive a migration exactly as the
    reference resumes computations from their replicated state.  A
    checkpoint ``resume`` takes precedence.  ``return_state=True``
    puts the final state (host arrays) on ``RunResult.state``.
    """
    t0 = time.perf_counter()
    sign = -1.0 if problem.maximize else 1.0

    if n_restarts < 1:
        raise ValueError(f"n_restarts must be >= 1, got {n_restarts}")
    batched_restarts = n_restarts > 1
    if batched_restarts and getattr(chunk_callback, "wants_values", False):
        raise ValueError(
            "n_restarts > 1 cannot feed a wants_values chunk_callback "
            "(the elastic runtime expects per-variable [n] values, not "
            "the [K, n] restart stack)"
        )

    fingerprint = None
    if checkpoint_path is not None:
        from pydcop_tpu.ops.compile import problem_fingerprint

        fingerprint = problem_fingerprint(problem)

    # The jitted path runs on a metadata-canonicalized copy: the jit
    # trace cache keys on every static pytree field, so variable/
    # constraint NAMES (host-only decode data) would otherwise force a
    # re-trace + XLA compile for each new problem object even when all
    # shapes match.  With the names stripped, any two problems that
    # agree on shapes and traced statics share one executable — the
    # reuse behind shape-bucketed dynamic segments (pad_policy) and
    # generated-instance sweeps.  The original is kept for decoding
    # and message accounting.
    host_problem = problem
    problem = canonical_execution_problem(problem)

    static_params = {
        k: v for k, v in params.items() if isinstance(v, (str, bool))
    }
    dyn_params = {
        k: jnp.asarray(v)
        for k, v in params.items()
        if not isinstance(v, (str, bool)) and v is not None
    }
    # params that only shape init_state (never the jitted step) stay
    # out of the runner closure and its cache key: a dynamic-run
    # segment switching to initial='declared' must not re-trace the
    # round loop it just compiled
    init_only = frozenset(
        getattr(algo_module, "INIT_ONLY_PARAMS", ("initial",))
    )
    step_statics = {
        k: v for k, v in static_params.items() if k not in init_only
    }

    axis_name = None
    if mesh is not None:
        from pydcop_tpu.parallel.mesh import SHARD_AXIS, shard_problem

        axis_name = SHARD_AXIS
        problem = shard_problem(problem, mesh)

    algo_step, cost_fn = _build_step(
        algo_module, step_statics, axis_name, n_restarts
    )

    cache_key_base = (
        algo_module.__name__,
        axis_name,
        tuple(sorted(step_statics.items())),
        tuple(sorted(dyn_params)),
        id(mesh) if mesh is not None else None,
        tuple(sorted(problem.buckets)),  # pspecs structure
        problem.n_shards,
        cost_every,
        n_restarts,
        # a mesh runner closes over problem-shaped in_specs whose
        # pytree AUX DATA (names, flags) must match the argument's —
        # two different problems with identical bucket structure would
        # otherwise reuse one runner and fail with a treedef mismatch
        # (dynamic runs recompile per segment and hit exactly this)
        jax.tree_util.tree_structure(problem) if mesh is not None else None,
        # instance-axis arity: 0 = this single-instance path; the
        # cross-instance path (run_many_batched) keys (K, donate) here
        # so a K-stacked vmapped runner can never serve a plain run
        0,
    )

    key = jax.random.PRNGKey(seed)
    k_init, k_run = jax.random.split(key)
    init_params = {
        **static_params, **{k: params[k] for k in dyn_params}
    }
    if initial_state is not None:
        # structural validation (the checkpoint resume path has meta
        # to check algo/seed/fingerprint; a raw pytree has only its
        # structure — validate everything it CAN prove): the 'values'
        # leaf must exist with the exact expected shape, and the leaf
        # set must match this algorithm's state, so a state from a
        # different algorithm, problem size, or restart count fails
        # loudly instead of continuing a foreign trajectory
        if (
            not isinstance(initial_state, dict)
            or "values" not in initial_state
        ):
            raise ValueError(
                "initial_state must be a state pytree with a "
                "'values' leaf (RunResult.state of a previous run)"
            )
        want = (
            (n_restarts, problem.n_vars)
            if batched_restarts
            else (problem.n_vars,)
        )
        got = tuple(jnp.shape(initial_state["values"]))
        if got != want:
            raise ValueError(
                f"initial_state 'values' has shape {got}, expected "
                f"{want} (n_restarts={n_restarts}, "
                f"n_vars={problem.n_vars}) — a state from a different "
                "problem or restart count?"
            )
        static_keys = frozenset(
            getattr(algo_module, "STATIC_STATE_KEYS", ())
        )
        expect_keys = (
            set(algo_module.init_state(problem, k_init, init_params))
            - static_keys
        )
        have_keys = set(initial_state) - static_keys
        if have_keys != expect_keys:
            raise ValueError(
                f"initial_state leaves {sorted(have_keys)} do not "
                f"match {algo_module.__name__}'s state "
                f"{sorted(expect_keys)} — a state from a different "
                "algorithm?"
            )
        state = jax.tree_util.tree_map(jnp.asarray, initial_state)
    elif batched_restarts:
        state = jax.vmap(
            lambda k: algo_module.init_state(problem, k, init_params)
        )(jax.random.split(k_init, n_restarts))
    else:
        state = algo_module.init_state(problem, k_init, init_params)
    best_values = state["values"]  # [R, n] under restarts
    if batched_restarts:
        # eager (outside shard_map): arrays are globally shaped here,
        # so no axis_name — the axis-aware cost_fn is runner-only
        best_cost = jax.vmap(
            lambda v: total_cost(problem, v)
        )(best_values)  # [R]
    else:
        best_cost = total_cost(problem, best_values)

    resumed_rounds = 0
    if resume and checkpoint_path is not None:
        import os

        from pydcop_tpu.engine.checkpoint import (
            checkpoint_meta,
            load_checkpoint,
        )

        if os.path.exists(checkpoint_path):
            # validate compatibility from the meta record BEFORE the
            # full load, so mismatches fail with the precise reason
            # (a K-mismatch would otherwise surface as a leaf-shape
            # "different problem?" error)
            meta = checkpoint_meta(checkpoint_path)
            if meta.get("algo") != algo_module.__name__:
                raise ValueError(
                    f"Checkpoint {checkpoint_path} was written by "
                    f"{meta.get('algo')}, not {algo_module.__name__}"
                )
            if meta.get("seed") != seed:
                raise ValueError(
                    f"Checkpoint {checkpoint_path} was written with "
                    f"seed {meta.get('seed')}, not {seed} — the RNG "
                    "stream would diverge"
                )
            if meta.get("chunk_size") not in (None, chunk_size):
                raise ValueError(
                    f"Checkpoint {checkpoint_path} was written with "
                    f"chunk_size {meta.get('chunk_size')}, not "
                    f"{chunk_size} — per-round keys are derived from "
                    "chunk boundaries, so the RNG stream would diverge"
                )
            if meta.get("problem") not in (None, fingerprint):
                raise ValueError(
                    f"Checkpoint {checkpoint_path} was written for a "
                    f"different problem instance (fingerprint "
                    f"{meta.get('problem')} != {fingerprint}) — "
                    "resuming would silently produce wrong results"
                )
            if meta.get("n_restarts", 1) != n_restarts:
                raise ValueError(
                    f"Checkpoint {checkpoint_path} was written with "
                    f"n_restarts={meta.get('n_restarts', 1)}, not "
                    f"{n_restarts} — the restart stack and RNG streams "
                    "would not line up"
                )
            state, bc, bv, resumed_rounds, _ = load_checkpoint(
                checkpoint_path,
                state,
                static_keys=getattr(algo_module, "STATIC_STATE_KEYS", ()),
            )
            state = jax.tree_util.tree_map(jnp.asarray, state)
            best_cost = jnp.asarray(bc, dtype=best_cost.dtype)
            best_values = jnp.asarray(bv, dtype=best_values.dtype)

    def _best_scalar(bc) -> float:
        return float(jnp.min(bc)) if batched_restarts else float(bc)

    def _full_state_specs():
        """The algorithm's declared state specs, completed with a
        replicated P() for any state leaf it does not name — optional
        leaves (e.g. maxsum's blockdiag index, present only under
        that belief mode) must not break the shard_map pytree match."""
        from jax.sharding import PartitionSpec as _P

        from pydcop_tpu.parallel.mesh import state_pspecs

        declared = state_pspecs(algo_module, problem)
        return {k: declared.get(k, _P()) for k in state}

    def _stacked(sspecs):
        """Prepend the restart axis (replicated) to every state spec:
        a [K, ...] restart stack shards exactly like [...] did."""
        if not batched_restarts:
            return sspecs
        return jax.tree_util.tree_map(
            lambda s: P(*((None,) + tuple(s))),
            sspecs,
            is_leaf=lambda x: isinstance(x, P),
        )

    met = get_metrics()

    def make_runner(n: int):
        cache_key = cache_key_base + (n,)
        if cache_key in _RUNNER_CACHE:
            if met.enabled:
                met.inc("engine.runner_cache_hits")
            _RUNNER_CACHE.move_to_end(cache_key)
            return _RUNNER_CACHE[cache_key]
        if met.enabled:
            met.inc("engine.runner_cache_misses")
        fn = _chunk_runner(algo_step, n, axis_name, cost_every, cost_fn)
        label = f"chunk[{algo_module.__name__.rsplit('.', 1)[-1]}:{n}]"
        if mesh is None:
            runner = profiled_jit(fn, label=label)
        else:
            from pydcop_tpu.parallel.mesh import (
                problem_pspecs,
                shard_map,
                state_pspecs,
            )

            pspecs = problem_pspecs(problem)
            sspecs = _stacked(_full_state_specs())
            dyn_specs = {k: P() for k in dyn_params}
            sharded = shard_map(
                fn,
                mesh=mesh,
                in_specs=(pspecs, sspecs, P(), dyn_specs, P(), P()),
                out_specs=(sspecs, P(), P(), P()),
                check_vma=False,
            )
            runner = profiled_jit(sharded, label=label)
        _RUNNER_CACHE[cache_key] = runner
        _evict_runners()
        return runner

    if mesh is not None:
        sspecs = _stacked(_full_state_specs())
        state = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            state,
            sspecs,
        )

    runner = make_runner(min(chunk_size, rounds))
    small_runner = None  # for the tail chunk, compiled lazily

    sup = get_supervisor()

    def _save_final_checkpoint():
        """Best-effort final checkpoint before an unrecoverable error
        surfaces: the postmortem (and a later ``resume=True`` retry)
        gets the last healthy carry instead of nothing."""
        if checkpoint_path is None:
            return
        from pydcop_tpu.engine.checkpoint import save_checkpoint

        try:
            save_checkpoint(
                checkpoint_path, state, best_cost, best_values, done,
                {
                    "algo": algo_module.__name__,
                    "seed": seed,
                    "chunk_size": chunk_size,
                    "problem": fingerprint,
                    "n_restarts": n_restarts,
                },
                static_keys=getattr(
                    algo_module, "STATIC_STATE_KEYS", ()
                ),
            )
            if met.enabled:
                met.inc("engine.checkpoints")
        except Exception:
            pass  # the original failure is the report, not this write

    traces = []
    done = resumed_rounds
    status = "finished"
    stall = 0
    chunks_since_save = 0
    prev_best = _best_scalar(best_cost)
    prev_values = np.asarray(best_values)
    tr = get_tracer()
    while done < rounds:
        this_chunk = min(chunk_size, rounds - done)
        if this_chunk == min(chunk_size, rounds):
            r = runner
        else:
            if small_runner is None or small_runner[0] != this_chunk:
                small_runner = (this_chunk, make_runner(this_chunk))
            r = small_runner[1]
        k_chunk = jax.random.fold_in(k_run, done)

        def _run_chunk(r=r, k_chunk=k_chunk):
            # force the cost trace to host INSIDE the supervised call:
            # with async dispatch, a runtime failure only surfaces at
            # this sync point, and it must surface where the
            # supervisor can classify it
            s, bc, bv, costs = r(
                problem, state, k_chunk, dyn_params, best_cost,
                best_values,
            )
            return s, bc, bv, np.asarray(costs)

        # the cycle span covers dispatch AND the host sync on the cost
        # trace — the wall-clock a chunk of rounds actually costs
        try:
            with tr.span(
                "cycle", cat="cycle", first=done, rounds=this_chunk
            ):
                state, best_cost, best_values, costs_np = sup.dispatch(
                    _run_chunk, scope="engine.chunk",
                    width=n_restarts, rounds=this_chunk,
                )
        except DeviceOOMError as e:
            # degradation ladder: halve the chunk down to the floor —
            # a shorter scan shrinks the live round-loop footprint.
            # The carries are untouched (this path never donates), so
            # the run resumes at the same boundary; per-round keys
            # derive from chunk boundaries, so stochastic RNG streams
            # differ from the fault-free run past this point (same
            # caveat as resuming with a different chunk_size).
            new_chunk = max(sup.chunk_floor, this_chunk // 2)
            if new_chunk >= this_chunk:
                _save_final_checkpoint()
                raise UnrecoverableDeviceError(
                    f"device OOM with the chunk already at the floor "
                    f"({this_chunk} rounds, chunk_floor="
                    f"{sup.chunk_floor}): {e}",
                    scope="engine.chunk", kind="oom",
                ) from e
            chunk_size = new_chunk
            if met.enabled:
                met.inc("engine.oom_chunk_halvings")
            if tr.enabled:
                tr.event(
                    "oom-halve", cat="supervisor", chunk=new_chunk,
                    round=done,
                )
            runner = make_runner(min(chunk_size, rounds))
            small_runner = None
            continue
        except UnrecoverableDeviceError:
            _save_final_checkpoint()
            raise
        if met.enabled:
            met.inc("engine.chunks")
            met.inc("engine.rounds", this_chunk)
        if batched_restarts:
            costs_np = costs_np.min(axis=-1)
        # numeric-fault screen at the chunk boundary (the nan_inject
        # seam): the cost trace is already on host, so the isnan scan
        # is free of device traffic.  NaN is poison, ±inf is a
        # legitimate hard-constraint cost.  The anytime best is
        # immune by construction (cost < best compares False for
        # NaN), so the degraded result carries the last finite best.
        poisoned = False
        if sup.active:
            if sup.nan_lanes(1, scope="engine.chunk"):
                costs_np = np.array(costs_np)  # device view: CoW
                costs_np[-1] = np.nan
            poisoned = bool(np.isnan(costs_np).any())
        traces.append(costs_np)
        done += this_chunk
        if poisoned:
            if met.enabled:
                met.inc("engine.numeric_faults")
            if sup.on_numeric_fault == "raise":
                _save_final_checkpoint()
                raise UnrecoverableDeviceError(
                    "NaN cost at a chunk boundary "
                    f"(round {done}) under on_numeric_fault='raise'",
                    scope="engine.chunk", kind="numeric",
                )
            if met.enabled:
                met.inc("engine.quarantined_instances")
            if tr.enabled:
                tr.event(
                    "quarantine", cat="supervisor",
                    scope="engine.chunk", round=done,
                )
            status = "degraded"
            break
        if checkpoint_path is not None:
            chunks_since_save += 1
            if chunks_since_save >= max(1, checkpoint_every):
                from pydcop_tpu.engine.checkpoint import save_checkpoint

                with tr.span("checkpoint", cat="checkpoint", round=done):
                    save_checkpoint(
                        checkpoint_path, state, best_cost, best_values,
                        done,
                        {
                            "algo": algo_module.__name__,
                            "seed": seed,
                            "chunk_size": chunk_size,
                            "problem": fingerprint,
                            "n_restarts": n_restarts,
                        },
                        static_keys=getattr(
                            algo_module, "STATIC_STATE_KEYS", ()
                        ),
                    )
                if met.enabled:
                    met.inc("engine.checkpoints")
                chunks_since_save = 0
        if chunk_callback is not None and done < rounds:
            # callbacks marked wants_values also receive the CURRENT
            # values array (the elastic runtime carries them across
            # cluster re-forms); the 2-arg form stays the default so
            # existing callbacks (orchestrator barrier, UI feed) are
            # untouched
            if getattr(chunk_callback, "wants_values", False):
                cb_status = chunk_callback(
                    done, _best_scalar(best_cost),
                    np.asarray(state["values"]),
                )
            else:
                cb_status = chunk_callback(done, _best_scalar(best_cost))
            if cb_status is not None:
                status = cb_status
                break
        if timeout is not None and time.perf_counter() - t0 > timeout:
            status = "timeout"
            break
        if convergence_chunks:
            # multi-restart: requiring ALL K instances to freeze would
            # effectively disable early stop (one mover blocks it), so
            # convergence is judged on the across-restart best alone —
            # and the [K, n] values stack never crosses to the host
            if batched_restarts:
                frozen = True
                cur_values = prev_values
            else:
                cur_values = np.asarray(state["values"])
                frozen = np.array_equal(cur_values, prev_values)
            if _best_scalar(best_cost) >= prev_best - 1e-9 and frozen:
                stall += 1
                if stall >= convergence_chunks:
                    status = "converged"
                    break
            else:
                stall = 0
            prev_best = _best_scalar(best_cost)
            prev_values = cur_values

    # a degraded (NaN-poisoned) state must never land in a checkpoint:
    # resuming from it would continue the poisoned trajectory
    if (
        checkpoint_path is not None
        and chunks_since_save
        and status != "degraded"
    ):
        from pydcop_tpu.engine.checkpoint import save_checkpoint

        save_checkpoint(
            checkpoint_path, state, best_cost, best_values,
            done,
            {
                "algo": algo_module.__name__,
                "seed": seed,
                "chunk_size": chunk_size,
                "problem": fingerprint,
                "n_restarts": n_restarts,
            },
            static_keys=getattr(algo_module, "STATIC_STATE_KEYS", ()),
        )

    final_values = state["values"]
    restart_costs = None
    if batched_restarts:
        # report the best restart: final = lowest final cost, anytime
        # best = lowest best-seen cost across all restarts (eager →
        # globally-shaped arrays, no axis_name)
        final_costs = jax.vmap(
            lambda v: total_cost(problem, v)
        )(final_values)
        i_fin = int(jnp.argmin(final_costs))
        final_values = final_values[i_fin]
        final_cost = float(final_costs[i_fin])
        i_best = int(jnp.argmin(best_cost))
        restart_costs = sign * np.asarray(best_cost)
        best_values = best_values[i_best]
        best_cost_f = float(best_cost[i_best])
    else:
        final_cost = float(total_cost(problem, final_values))
        best_cost_f = float(best_cost)
    if status == "degraded":
        # the post-poison final values are not trusted — report the
        # anytime best for both, the same contract as the message
        # plane's degraded results (docs/faults.md)
        final_values = best_values
        final_cost = best_cost_f
    elapsed = time.perf_counter() - t0
    msgs = (
        algo_module.messages_per_round(host_problem, params)
        * done
        * n_restarts
    )
    trace = np.concatenate(traces) if traces else np.zeros(0)
    out_state = None
    # a degraded run's state pytree is (potentially) NaN-poisoned —
    # never hand it out as a carry for a next segment
    if return_state and status != "degraded":
        def _to_host(x):
            try:
                return np.asarray(x)
            except RuntimeError:
                # multi-host mesh: the global array spans
                # non-addressable devices — keep the jax array, which
                # is still a valid initial_state for a next segment
                # on the same global mesh
                return x

        out_state = jax.tree_util.tree_map(_to_host, state)
    return RunResult(
        assignment=decode_assignment(host_problem, final_values),
        cost=sign * final_cost,
        best_assignment=decode_assignment(host_problem, best_values),
        best_cost=sign * best_cost_f,
        cycles=done,
        messages=msgs,
        time=elapsed,
        status=status,
        cost_trace=sign * trace,
        restart_costs=restart_costs,
        state=out_state,
    )


def run_many_batched(
    stacked,
    algo_module,
    params: Union[Mapping[str, Any], Sequence[Mapping[str, Any]]],
    *,
    rounds: int = 100,
    seeds: Union[int, Sequence[int]] = 0,
    timeout: Optional[float] = None,
    chunk_size: int = 64,
    convergence_chunks: int = 0,
    cost_every: int = 1,
    n_restarts: int = 1,
    mesh=None,
    donate: bool = True,
    _attempt: int = 0,
) -> List[RunResult]:
    """Solve K same-bucket problem instances in ONE device program.

    ``stacked`` is a :class:`~pydcop_tpu.ops.compile.StackedProblem`
    (from :func:`~pydcop_tpu.ops.compile.stack_problems`): K problems
    whose canonical forms share shapes and traced statics, stacked
    along a leading ``instance`` axis.  The chunk runner is the SAME
    scan :func:`run_batched` compiles, ``jax.vmap``-ed over that axis
    — so K instances cost one XLA compile and one device-program
    launch per chunk instead of K, and the per-round math vectorizes
    across instances.  The instance axis composes orthogonally with
    the restart axis (``n_restarts > 1`` ⇒ carries are
    ``[K, R, ...]``) and with a ``mesh`` (the vmap wraps the
    ``shard_map``-ed runner; constraint/edge arrays shard per
    instance, the instance axis stays replicated).

    Per-instance RNG parity: instance ``i`` consumes EXACTLY the key
    stream a sequential ``run_batched(problems[i], seed=seeds[i])``
    would (``PRNGKey → split → per-chunk fold_in``), so deterministic
    algorithms return bit-identical results either way (tested;
    ``seeds`` an int applies to every instance).  ``params`` may be a
    single mapping (shared) or one mapping per instance — numeric
    params may differ per instance (they ride the vmap as stacked
    arrays); str/bool params are baked into the step and must agree
    across the stack (group by them upstream).

    ``donate=True`` donates the chunk carries (state, best cost/values)
    to the jitted runner (``donate_argnums``) so the K-instance state
    ping-pongs between two buffers instead of reallocating per chunk —
    the memory-pressure lever at large K.  Donation changes the cache
    key (a donated executable aliases its buffers).

    ``timeout`` and ``convergence_chunks`` act on the whole stack at
    chunk boundaries: the run stops for ALL instances together —
    converged only when no instance's best improved (and, without
    restarts, no instance's values changed) for that many consecutive
    chunks.  Per-instance early exit does not compose with one fused
    program; callers needing it should solve sequentially.

    Returns one :class:`RunResult` per instance in STACK order
    (``stacked.indices`` maps back to the caller's input order).
    Each result's ``time`` is the whole group's wall-clock — divide by
    ``stacked.n_instances`` for a per-instance share.
    """
    t0 = time.perf_counter()
    K = stacked.n_instances
    template = stacked.template
    sign = -1.0 if template.maximize else 1.0  # uniform per group key

    if n_restarts < 1:
        raise ValueError(f"n_restarts must be >= 1, got {n_restarts}")
    batched_restarts = n_restarts > 1

    if isinstance(params, Mapping):
        params_list = [dict(params)] * K
    else:
        params_list = [dict(p) for p in params]
    if len(params_list) != K:
        raise ValueError(
            f"params: got {len(params_list)} dicts for "
            f"{K} instances"
        )

    def _split(p):
        statics = {
            k: v for k, v in p.items() if isinstance(v, (str, bool))
        }
        dyn = {
            k: v
            for k, v in p.items()
            if not isinstance(v, (str, bool)) and v is not None
        }
        return statics, dyn

    static_params, _dyn0 = _split(params_list[0])
    dyn_keys = tuple(sorted(_dyn0))
    for i, p in enumerate(params_list[1:], 1):
        s, d = _split(p)
        if s != static_params or tuple(sorted(d)) != dyn_keys:
            raise ValueError(
                f"run_many_batched: instance {i} differs from "
                "instance 0 in static (str/bool) params or param "
                "structure — statics are baked into the compiled "
                "step; group instances by them upstream"
            )
    dyn_params = {
        k: jnp.stack([jnp.asarray(p[k]) for p in params_list])
        for k in dyn_keys
    }
    init_only = frozenset(
        getattr(algo_module, "INIT_ONLY_PARAMS", ("initial",))
    )
    step_statics = {
        k: v for k, v in static_params.items() if k not in init_only
    }

    if isinstance(seeds, (int, np.integer)):
        seeds = [int(seeds)] * K
    else:
        seeds = [int(s) for s in seeds]
    if len(seeds) != K:
        raise ValueError(
            f"seeds: got {len(seeds)} for {K} instances"
        )

    problem = stacked.problem
    axis_name = None
    if mesh is not None:
        from pydcop_tpu.parallel.mesh import SHARD_AXIS, problem_pspecs

        axis_name = SHARD_AXIS
        # shard each instance's constraint/edge arrays over the mesh;
        # the INSTANCE axis is vmapped, not mesh-mapped, so it stays
        # replicated (a None prepended to every pspec)
        pspecs = problem_pspecs(template)
        problem = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(
                x, NamedSharding(mesh, P(*((None,) + tuple(s))))
            ),
            problem,
            pspecs,
        )

    algo_step, cost_fn = _build_step(
        algo_module, step_statics, axis_name, n_restarts
    )

    cache_key_base = (
        algo_module.__name__,
        axis_name,
        tuple(sorted(step_statics.items())),
        dyn_keys,
        id(mesh) if mesh is not None else None,
        tuple(sorted(template.buckets)),
        template.n_shards,
        cost_every,
        n_restarts,
        jax.tree_util.tree_structure(problem) if mesh is not None else None,
        # instance-axis arity + donation (a donated executable aliases
        # its carry buffers — it must never serve a non-donating call)
        K,
        bool(donate),
    )

    # per-instance key streams, EXACTLY as K sequential run_batched
    # calls would derive them: PRNGKey(seed) → split → fold_in(chunk)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.asarray(seeds))
    ks = jax.vmap(jax.random.split)(keys)  # [K, 2, 2]
    k_init, k_run = ks[:, 0], ks[:, 1]

    def _init_one(p, k, dyn):
        ip = {**static_params, **dyn}
        if batched_restarts:
            return jax.vmap(
                lambda kk: algo_module.init_state(p, kk, ip)
            )(jax.random.split(k, n_restarts))
        return algo_module.init_state(p, k, ip)

    state = jax.vmap(_init_one)(problem, k_init, dyn_params)
    # copy: 'values' is about to be donated as BOTH a state leaf and
    # the best_values carry — aliased donated inputs are not allowed
    best_values = jnp.array(state["values"], copy=True)
    if batched_restarts:
        best_cost = jax.vmap(
            lambda p, vs: jax.vmap(lambda v: total_cost(p, v))(vs)
        )(problem, best_values)  # [K, R]
    else:
        best_cost = jax.vmap(total_cost)(problem, best_values)  # [K]

    def _sspecs(instance_axis: bool):
        """State pspecs completed with replicated P() for undeclared
        leaves, with the restart axis (always, when enabled) and
        optionally the instance axis prepended as replicated."""
        from pydcop_tpu.parallel.mesh import state_pspecs

        declared = state_pspecs(algo_module, template)
        specs = {k: declared.get(k, P()) for k in state}
        prefix = (None,) * (
            (1 if instance_axis else 0) + (1 if batched_restarts else 0)
        )
        return jax.tree_util.tree_map(
            lambda s: P(*(prefix + tuple(s))),
            specs,
            is_leaf=lambda x: isinstance(x, P),
        )

    if mesh is not None:
        state = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            state,
            _sspecs(instance_axis=True),
        )

    met = get_metrics()
    # counted on the FIRST successful dispatch: a group that OOMs
    # before running any chunk re-enters as two half-groups, and only
    # the groups that actually executed should land on the counters
    group_counted = False

    def make_runner(n: int):
        cache_key = cache_key_base + (n,)
        if cache_key in _RUNNER_CACHE:
            if met.enabled:
                met.inc("engine.runner_cache_hits")
            _RUNNER_CACHE.move_to_end(cache_key)
            return _RUNNER_CACHE[cache_key]
        if met.enabled:
            met.inc("engine.runner_cache_misses")
        fn = _chunk_runner(algo_step, n, axis_name, cost_every, cost_fn)
        label = (
            f"chunk[{algo_module.__name__.rsplit('.', 1)[-1]}:{n}x{K}]"
        )
        if mesh is not None:
            from pydcop_tpu.parallel.mesh import (
                problem_pspecs,
                shard_map,
            )

            pspecs = problem_pspecs(template)
            sspecs = _sspecs(instance_axis=False)
            dyn_specs = {k: P() for k in dyn_params}
            fn = shard_map(
                fn,
                mesh=mesh,
                in_specs=(pspecs, sspecs, P(), dyn_specs, P(), P()),
                out_specs=(sspecs, P(), P(), P()),
                check_vma=False,
            )
        # the instance vmap: every argument — problem data, carries,
        # keys AND numeric params — maps over its leading axis
        vfn = jax.vmap(fn, in_axes=(0, 0, 0, 0, 0, 0))
        runner = profiled_jit(
            vfn,
            label=label,
            **({"donate_argnums": (1, 4, 5)} if donate else {}),
        )
        _RUNNER_CACHE[cache_key] = runner
        _evict_runners()
        return runner

    runner = make_runner(min(chunk_size, rounds))
    small_runner = None

    sup = get_supervisor()

    def _split_and_rerun(cause: BaseException) -> List[RunResult]:
        """OOM degradation for a stacked group: split the instance
        stack in half and re-dispatch each half as its own (recursive)
        ``run_many_batched`` call from round 0.

        Stream-preserving by construction — every instance keeps its
        own seed and the same chunk schedule, so the halves' results
        are bit-identical to the fault-free group run.  Equal-sized
        halves also share ONE vmapped runner cache entry (the cache
        keys on K), so a split costs at most one extra compile per
        distinct half size (``tools/recompile_guard.py:
        run_supervisor_guard`` pins this).  Restarting from round 0
        discards at most the chunks already run — real OOM almost
        always fires on the FIRST dispatch of an over-wide group, and
        the injected capacity model always does."""
        if met.enabled:
            met.inc("engine.oom_splits")
        tr = get_tracer()
        if tr.enabled:
            tr.event(
                "oom-split", cat="supervisor", scope="engine.group",
                instances=K, error=str(cause)[:200],
            )
        from pydcop_tpu.ops.compile import stack_problems

        mid = (K + 1) // 2
        out: List[RunResult] = []
        for lo, hi in ((0, mid), (mid, K)):
            halves = stack_problems(stacked.host_problems[lo:hi])
            # same bucket by construction: one group comes back
            half = halves[0]
            remaining = (
                None
                if timeout is None
                else max(timeout - (time.perf_counter() - t0), 0.01)
            )
            out.extend(
                run_many_batched(
                    half,
                    algo_module,
                    params_list[lo:hi],
                    rounds=rounds,
                    seeds=seeds[lo:hi],
                    timeout=remaining,
                    chunk_size=chunk_size,
                    convergence_chunks=convergence_chunks,
                    cost_every=cost_every,
                    n_restarts=n_restarts,
                    mesh=mesh,
                    donate=donate,
                )
            )
        return out

    def _restart_group(
        new_chunk: Optional[int] = None, attempt: int = 0
    ) -> List[RunResult]:
        """Caller-level recovery when the donated carries are gone: a
        REAL failure surfaces at the sync point, AFTER the donated
        dispatch consumed its input buffers, so re-dispatching in
        place would touch deleted arrays.  Re-enter the WHOLE group
        from round 0 instead — the host-side stacks are intact, the
        runner cache is warm (zero recompiles), and the replay is
        stream-preserving (same seeds, same chunk schedule unless
        ``new_chunk`` shrinks it)."""
        remaining = (
            None
            if timeout is None
            else max(timeout - (time.perf_counter() - t0), 0.01)
        )
        return run_many_batched(
            stacked,
            algo_module,
            params_list,
            rounds=rounds,
            seeds=seeds,
            timeout=remaining,
            chunk_size=new_chunk or chunk_size,
            convergence_chunks=convergence_chunks,
            cost_every=cost_every,
            n_restarts=n_restarts,
            mesh=mesh,
            donate=donate,
            _attempt=attempt,
        )

    def _per_instance_best(bc: np.ndarray) -> np.ndarray:
        return bc.min(axis=-1) if batched_restarts else bc

    traces: List[np.ndarray] = []
    done = 0
    status = "finished"
    stall = 0
    # lane -> (best_cost row, best_values row) snapshot at the
    # boundary the lane went numerically poisoned: the group keeps
    # running for the healthy K-1 lanes, the quarantined lane reports
    # this last-finite anytime best with status='degraded'
    quarantined: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
    prev_best = _per_instance_best(np.asarray(best_cost))
    prev_values = np.asarray(best_values)
    tr = get_tracer()
    while done < rounds:
        this_chunk = min(chunk_size, rounds - done)
        if this_chunk == min(chunk_size, rounds):
            r = runner
        else:
            if small_runner is None or small_runner[0] != this_chunk:
                small_runner = (this_chunk, make_runner(this_chunk))
            r = small_runner[1]
        k_chunk = jax.vmap(
            lambda k: jax.random.fold_in(k, done)
        )(k_run)

        def _run_chunk(r=r, k_chunk=k_chunk):
            s, bc, bv, costs = r(
                problem, state, k_chunk, dyn_params, best_cost,
                best_values,
            )
            return s, bc, bv, np.asarray(costs)

        try:
            with tr.span(
                "cycle", cat="cycle", first=done, rounds=this_chunk,
                instances=K,
            ):
                state, best_cost, best_values, costs_np = sup.dispatch(
                    _run_chunk, scope="engine.group",
                    width=K * n_restarts, rounds=this_chunk,
                    # donated carries are consumed AT dispatch, so a
                    # real failure surfacing at the sync point cannot
                    # be replayed in place — the supervisor hands it
                    # back (DeviceTransientError) for the group
                    # restart below instead
                    retryable=not donate,
                )  # costs_np: [K, samples(, R)]
        except DeviceTransientError as e:
            # real transient after the donated carries were consumed:
            # the retry is a whole-group restart from round 0 —
            # bit-identical to an uninterrupted run, warm-cache cheap
            if _attempt >= sup.config.retry_budget:
                raise UnrecoverableDeviceError(
                    "engine.group: transient device failure "
                    "persisted through the retry budget "
                    f"({sup.config.retry_budget}) across group "
                    f"restarts: {e}",
                    scope="engine.group", kind="transient",
                    attempts=_attempt,
                ) from e
            if met.enabled:
                met.inc("engine.retries")
            if tr.enabled:
                tr.event(
                    "group-restart", cat="supervisor",
                    scope="engine.group", attempt=_attempt + 1,
                    error=str(e)[:200],
                )
            delays = backoff_delays(
                base=sup.config.backoff_base,
                factor=sup.config.backoff_factor,
                max_delay=sup.config.backoff_max,
                jitter=sup.config.backoff_jitter,
                seed=(
                    sup.config.plan.seed
                    if sup.config.plan is not None
                    else 0
                ),
                key="supervisor:engine.group.restart",
            )
            for _ in range(_attempt):  # pure keyed stream: skip to
                next(delays)  # this restart's attempt position
            sup.config.sleep(next(delays))
            return _restart_group(attempt=_attempt + 1)
        except DeviceOOMError as e:
            if K > 1:
                return _split_and_rerun(e)
            # single-lane group: the same chunk-halving ladder as
            # run_batched, then genuinely over capacity
            new_chunk = max(sup.chunk_floor, this_chunk // 2)
            if new_chunk >= this_chunk:
                raise UnrecoverableDeviceError(
                    f"device OOM on a single-instance group with the "
                    f"chunk already at the floor ({this_chunk} "
                    f"rounds, chunk_floor={sup.chunk_floor}): {e}",
                    scope="engine.group", kind="oom",
                ) from e
            if met.enabled:
                met.inc("engine.oom_chunk_halvings")
            if tr.enabled:
                tr.event(
                    "oom-halve", cat="supervisor", chunk=new_chunk,
                    round=done,
                )
            if donate and not e.injected:
                # real allocation failure after the donated carries
                # were consumed: the in-place continue below would
                # touch deleted buffers — restart from round 0 at the
                # halved chunk instead (injected OOM fires BEFORE
                # dispatch, so its carries are intact)
                return _restart_group(
                    new_chunk=new_chunk, attempt=_attempt
                )
            chunk_size = new_chunk
            runner = make_runner(min(chunk_size, rounds))
            small_runner = None
            continue
        if not group_counted:
            group_counted = True
            if met.enabled:
                met.inc("engine.batch_groups")
                met.inc("engine.instances_batched", K)
        if met.enabled:
            met.inc("engine.chunks")
            met.inc("engine.rounds", this_chunk)
        if batched_restarts:
            costs_np = costs_np.min(axis=-1)
        # per-lane numeric-fault screen (and the nan_inject seam):
        # one isnan scan over the already-on-host cost trace.  A
        # poisoned lane is quarantined — snapshotted and reported
        # degraded — while the other K-1 lanes keep running
        # bit-identically (vmap lanes never exchange data)
        if sup.active:
            lanes = sup.nan_lanes(K, scope="engine.group")
            if lanes:
                costs_np = np.array(costs_np)  # device view: CoW
                for lane in lanes:
                    costs_np[lane, -1] = np.nan
            bad = np.isnan(costs_np).any(axis=1)
            new_bad = [
                int(i)
                for i in np.nonzero(bad)[0]
                if int(i) not in quarantined
            ]
            if new_bad:
                if met.enabled:
                    met.inc("engine.numeric_faults", len(new_bad))
                if sup.on_numeric_fault == "raise":
                    raise UnrecoverableDeviceError(
                        f"NaN cost in instance lane(s) {new_bad} at "
                        f"round {done + this_chunk} under "
                        "on_numeric_fault='raise'",
                        scope="engine.group", kind="numeric",
                    )
                bc_np = np.asarray(best_cost)
                bv_np = np.asarray(best_values)
                for i in new_bad:
                    quarantined[i] = (
                        np.array(bc_np[i]), np.array(bv_np[i]),
                    )
                    if met.enabled:
                        met.inc("engine.quarantined_instances")
                    if tr.enabled:
                        tr.event(
                            "quarantine", cat="supervisor",
                            scope="engine.group", lane=i,
                            round=done + this_chunk,
                        )
        traces.append(costs_np)
        done += this_chunk
        if len(quarantined) == K:
            # nothing healthy left to run rounds for
            status = "degraded"
            break
        if timeout is not None and time.perf_counter() - t0 > timeout:
            status = "timeout"
            break
        if convergence_chunks:
            bc_np = _per_instance_best(np.asarray(best_cost))
            if batched_restarts:
                frozen = True
                cur_values = prev_values
            else:
                cur_values = np.asarray(state["values"])
                frozen = np.array_equal(cur_values, prev_values)
            if np.all(bc_np >= prev_best - 1e-9) and frozen:
                stall += 1
                if stall >= convergence_chunks:
                    status = "converged"
                    break
            else:
                stall = 0
            prev_best = bc_np
            prev_values = cur_values

    # unstack: per-instance final/best selection on the host
    final_values = np.asarray(state["values"])  # [K(, R), n]
    best_values_np = np.asarray(best_values)
    best_cost_np = np.asarray(best_cost)
    restart_costs_np = None
    if batched_restarts:
        final_costs = np.asarray(
            jax.vmap(
                lambda p, vs: jax.vmap(lambda v: total_cost(p, v))(vs)
            )(problem, state["values"])
        )  # [K, R]
        i_fin = final_costs.argmin(axis=1)
        rows = np.arange(K)
        fv = final_values[rows, i_fin]
        fc = final_costs[rows, i_fin]
        i_best = best_cost_np.argmin(axis=1)
        restart_costs_np = sign * best_cost_np  # [K, R]
        bv = best_values_np[rows, i_best]
        bc = best_cost_np[rows, i_best]
    else:
        fc = np.asarray(
            jax.vmap(total_cost)(problem, state["values"])
        )
        fv = np.array(final_values)
        bv, bc = np.array(best_values_np), best_cost_np
    fc = np.array(fc, dtype=np.float64)
    bc = np.array(bc, dtype=np.float64)
    statuses = [status] * K
    for i, (q_bc, q_bv) in quarantined.items():
        # the lane's post-poison device values are not trusted:
        # report its snapshot (last-finite anytime best) as BOTH
        # final and best, the message-plane degraded contract
        statuses[i] = "degraded"
        if batched_restarts:
            j = int(np.argmin(q_bc))
            restart_costs_np[i] = sign * q_bc
            lane_bv, lane_bc = q_bv[j], float(q_bc[j])
        else:
            lane_bv, lane_bc = q_bv, float(q_bc)
        fv[i] = lane_bv
        bv[i] = lane_bv
        fc[i] = lane_bc
        bc[i] = lane_bc
    elapsed = time.perf_counter() - t0
    trace = (
        np.concatenate(traces, axis=1)
        if traces
        else np.zeros((K, 0))
    )
    results: List[RunResult] = []
    for i, hp in enumerate(stacked.host_problems):
        msgs = (
            algo_module.messages_per_round(hp, params_list[i])
            * done
            * n_restarts
        )
        results.append(
            RunResult(
                assignment=decode_assignment(hp, fv[i]),
                cost=sign * float(fc[i]),
                best_assignment=decode_assignment(hp, bv[i]),
                best_cost=sign * float(bc[i]),
                cycles=done,
                messages=msgs,
                time=elapsed,
                status=statuses[i],
                cost_trace=sign * trace[i],
                restart_costs=(
                    restart_costs_np[i] if batched_restarts else None
                ),
            )
        )
    return results


# statics_signature / run_many_host live in the jax-free
# engine.host_batch module (api.solve_many's host branch must not pay
# this module's jax import chain); re-exported here for the
# engine.batched.* names used before the split.
from pydcop_tpu.engine.host_batch import (  # noqa: E402
    run_many_host,
    statics_signature,
)
