"""Run-state checkpointing (an upgrade over the reference, SURVEY §5:
the reference only "checkpoints" by replicating computations to other
agents' memory; here the whole solve state is a pytree of arrays, so a
real checkpoint is one ``.npz`` file).

A checkpoint stores every leaf of the algorithm's state pytree (keyed
by its tree path), the anytime-best cost/values, and the round counter.
Restore rebuilds the exact pytree using a freshly-initialized state of
the same structure as the template — no pickling, no code execution on
load.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, Tuple

import jax
import numpy as np

_META_KEY = "__meta__"


def _leaf_key(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)


def save_checkpoint(
    path: str,
    state,
    best_cost,
    best_values,
    rounds_done: int,
    extra_meta: Dict[str, Any] = None,
    static_keys=(),
) -> None:
    """Atomically write the run state to ``path`` (.npz).

    ``best_cost`` is a scalar, or a [K] vector for a multi-restart run
    (the per-restart anytime bests — ``best_values`` is then the
    [K, n] stack).  Leaves under ``static_keys`` are SKIPPED: the load
    side backfills them from a freshly-initialized template anyway
    (they are pure problem-derived index data), so writing them —
    e.g. maxsum's dense blockdiag incidence — would be wasted I/O."""
    leaves = {}
    for kpath, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        if _leaf_key(kpath[:1]) in static_keys:
            continue
        leaves[f"state/{_leaf_key(kpath)}"] = np.asarray(leaf)
    leaves["best_values"] = np.asarray(best_values)
    meta = {
        "best_cost": np.asarray(best_cost).tolist(),
        "rounds_done": int(rounds_done),
        **(extra_meta or {}),
    }
    leaves[_META_KEY] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8
    )
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **leaves)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def checkpoint_meta(path: str) -> Dict[str, Any]:
    """Read only the metadata record — callers validate compatibility
    (algo, seed, chunk size, problem fingerprint, n_restarts) BEFORE
    paying the full load, and with precise error messages."""
    with np.load(path) as data:
        return json.loads(bytes(data[_META_KEY]).decode())


def load_checkpoint(
    path: str, state_template, static_keys=()
) -> Tuple[Any, Any, np.ndarray, int, Dict[str, Any]]:
    """Restore ``(state, best_cost, best_values, rounds_done, meta)``.

    ``best_cost`` is a float for single runs, or a length-K list for
    multi-restart checkpoints (``best_values`` is then ``[K, n]``).

    ``state_template`` (a freshly-initialized state of the same
    algorithm/problem) provides the pytree structure; every leaf must be
    present in the checkpoint with a matching shape, EXCEPT leaves
    whose top-level key is in ``static_keys`` (an algorithm module's
    ``STATIC_STATE_KEYS``): those are pure problem-derived index data
    that ``init_state`` rebuilds identically, so a missing or stale
    copy in the file is backfilled from the template — this keeps
    checkpoints from older builds resumable when an algorithm grows a
    new static index.
    """
    with np.load(path) as data:
        meta = json.loads(bytes(data[_META_KEY]).decode())
        paths_leaves = jax.tree_util.tree_flatten_with_path(state_template)
        leaves = []
        for kpath, tmpl in paths_leaves[0]:
            key = f"state/{_leaf_key(kpath)}"
            top = _leaf_key(kpath[:1])
            if top in static_keys:
                leaves.append(np.asarray(tmpl))
                continue
            if key not in data:
                raise ValueError(
                    f"Checkpoint {path} misses state leaf {key!r} — "
                    "was it written by a different algorithm?"
                )
            arr = data[key]
            if arr.shape != np.shape(tmpl):
                raise ValueError(
                    f"Checkpoint leaf {key!r} has shape {arr.shape}, "
                    f"expected {np.shape(tmpl)} — different problem?"
                )
            tdt = np.dtype(getattr(tmpl, "dtype", arr.dtype))
            if arr.dtype.kind == "V":
                # np.savez stores ml_dtypes arrays (bfloat16 message
                # state, msg_dtype='bf16') as raw void records; the
                # template knows the real dtype — reinterpret, never
                # numerically convert
                if tdt.itemsize != arr.dtype.itemsize:
                    raise ValueError(
                        f"Checkpoint leaf {key!r} has an opaque "
                        f"{arr.dtype.itemsize}-byte dtype; the state "
                        f"template expects {tdt} — different "
                        "msg_dtype setting?"
                    )
                arr = arr.view(tdt)
            elif arr.dtype != tdt:
                # both directions must fail loudly: an f32 checkpoint
                # resumed under msg_dtype='bf16' would otherwise run
                # the whole job in f32 while the params claim bf16
                raise ValueError(
                    f"Checkpoint leaf {key!r} has dtype {arr.dtype}, "
                    f"the state template expects {tdt} — different "
                    "msg_dtype (or algorithm parameter) setting?"
                )
            leaves.append(arr)
        state = jax.tree_util.tree_unflatten(paths_leaves[1], leaves)
        best_values = data["best_values"]
    return (
        state,
        meta["best_cost"],  # scalar, or [K] list for restart stacks
        best_values,
        int(meta["rounds_done"]),
        meta,
    )
