"""Subtree-fingerprint message memoization — the O(delta) re-solve
path for serving sessions (ROADMAP item 2; ISSUE 18).

A ``set_values`` delta touches a handful of constraints, and a
bucket-tree UTIL/contraction message depends ONLY on its subtree:
``msg(v) = ⊕-project( ⊗ own parts(v) ⊗ children msgs )``.  So a
node whose subtree saw no touched constraint must reproduce its
previous message bit-for-bit — the classic incremental view
maintenance argument over semiring aggregates (arXiv:1703.03147)
applied to the FAQ-style sweeps (arXiv:1504.04044) this repo runs.

:class:`SweepMemo` stores per-node messages in a bounded (bytes) LRU
keyed by a **subtree fingerprint**: the tuple of effective external
values the node's subtree depends on — the same base-hash +
effective-external-values discipline ``engine/incremental.py`` uses
for compiled tables, applied per pseudo-tree node.  A re-solve then
re-contracts ONLY the dirty root-to-changed-constraint path; every
other node is a memo hit that reinstalls the stored message (exact
f64 values + f32-certificate metadata for idempotent ⊕, the
CUMULATIVE subtree error bound for logsumexp — so the dirty path
re-accounts only its own error).

Warm deltas also do **zero XLA compiles**: the sweeps dispatch dirty
buckets through the stacked (vmapped) kernels even at one row, and
after a cold solve the memo pre-warms the stack-height-1 variant of
every level-pack kernel the sweep used, so the lone dirty row of a
follow-up lands on an already-compiled executable.

Two session front-ends wrap the machinery:

- :class:`ExactSession` — DPOP (``algorithms/dpop.py``): memoized
  UTIL sweeps, previous-solution incumbent seeding for the bnb
  kernels, reference-shaped result dicts.
- :class:`InferSession` — the semiring engine
  (``ops/semiring.py:contract_sweep``): memoized contraction sweeps
  for ``map`` / ``log_z`` / ``marginals`` / ``kbest:<k>`` queries.

Telemetry (``docs/observability.md``): ``engine.memo_hits`` (nodes
reused), ``engine.memo_recontractions`` (nodes re-contracted and
re-stored), ``engine.memo_evictions`` (entries dropped by the bytes
bound).
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

#: default per-session memo bound — a few thousand small UTIL tables;
#: large-separator trees evict LRU (the deep entries near the root,
#: which are also the cheapest to re-contract, evict last because the
#: sweep touches them last)
DEFAULT_MEMO_BYTES = 64 << 20


def _nbytes(obj: Any) -> int:
    """Recursive payload size estimate (arrays dominate; container
    overhead is charged a flat word per element)."""
    if isinstance(obj, np.ndarray):
        return int(obj.nbytes)
    from pydcop_tpu.ops.sparse import SparseTable

    if isinstance(obj, SparseTable):
        # charge the PACKED footprint — memoizing a sparse message at
        # its dense box size would evict the very entries the format
        # exists to keep
        return int(obj.nbytes)
    if isinstance(obj, (tuple, list)):
        return 16 + sum(_nbytes(x) for x in obj)
    if isinstance(obj, dict):
        return 16 + sum(
            _nbytes(k) + _nbytes(v) for k, v in obj.items()
        )
    if isinstance(obj, str):
        return 48 + len(obj)
    return 32


class SweepMemo:
    """Bounded per-session store of per-node sweep messages plus the
    level-pack kernel specs a pre-warm needs (module docstring).

    ``max_bytes <= 0`` disables the memo entirely: :meth:`begin`
    returns None and the sweeps run exactly as before."""

    def __init__(self, max_bytes: int = DEFAULT_MEMO_BYTES):
        self.max_bytes = int(max_bytes)
        # name -> (fingerprint, payload, nbytes); OrderedDict = LRU
        self._entries: "OrderedDict[str, Tuple[tuple, Any, int]]" = (
            OrderedDict()
        )
        self._bytes = 0
        self.evictions = 0
        # (sr_name, pshape, part_shapes, use_bnb, table_dtype) specs
        # of every stacked kernel a memoized sweep dispatched —
        # prewarm() compiles their stack-height-1 variants so a warm
        # delta's lone dirty row never triggers an XLA compile
        self._kernel_specs: "OrderedDict[tuple, None]" = OrderedDict()
        self._prewarmed: set = set()

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    def begin(
        self, fps: Mapping[str, tuple]
    ) -> Optional["SweepMemoView"]:
        """A per-solve view bound to the solve's current per-node
        subtree fingerprints; None when the memo is disabled."""
        if not self.enabled:
            return None
        return SweepMemoView(self, dict(fps))

    # -- store ------------------------------------------------------------

    def _get(self, name: str, fp: tuple):
        ent = self._entries.get(name)
        if ent is None or ent[0] != fp:
            return None
        self._entries.move_to_end(name)
        return ent[1]

    def _put(self, name: str, fp: tuple, payload: Any) -> None:
        old = self._entries.pop(name, None)
        if old is not None:
            self._bytes -= old[2]
        nb = _nbytes(payload)
        if nb > self.max_bytes:
            return  # one oversized table must not flush the session
        self._entries[name] = (fp, payload, nb)
        self._bytes += nb
        if self._bytes > self.max_bytes:
            from pydcop_tpu.telemetry import get_metrics

            met = get_metrics()
            while self._bytes > self.max_bytes and self._entries:
                _, (_, _, enb) = self._entries.popitem(last=False)
                self._bytes -= enb
                self.evictions += 1
                if met.enabled:
                    met.inc("engine.memo_evictions")

    # -- kernel pre-warm --------------------------------------------------

    def note_kernel(
        self,
        sr_name: str,
        pshape: Tuple[int, ...],
        part_shapes: Tuple[Tuple[int, ...], ...],
        use_bnb: bool,
        table_dtype: str = "f32",
        table_format: str = "dense",
    ) -> None:
        # sparse specs reuse the slots: pshape = (n_cand_b, n_seg_b),
        # part_shapes = the packed part lengths (ints)
        self._kernel_specs[
            (
                sr_name, tuple(pshape), tuple(part_shapes),
                bool(use_bnb), str(table_dtype), str(table_format),
            )
        ] = None

    def prewarm(self, heights: Sequence[int] = (1,)) -> int:
        """Compile the stacked kernels of every recorded spec at the
        given stack heights (default: the 1-row variant a 1-delta
        follow-up dispatches).  Runs after a solve, so the compile
        cost lands in the COLD segment, never on a warm delta.
        Returns the number of kernel executions performed."""
        from pydcop_tpu.ops.semiring import (
            _np_table_dtype,
            contraction_kernel,
            get_semiring,
        )

        n = 0
        for spec in list(self._kernel_specs):
            (sr_name, pshape, part_shapes, use_bnb, table_dtype,
             table_format) = spec
            for h in heights:
                if (spec, h) in self._prewarmed:
                    continue
                if table_format == "sparse":
                    self._prewarm_sparse(
                        sr_name, pshape, part_shapes, use_bnb,
                        table_dtype, h,
                    )
                    self._prewarmed.add((spec, h))
                    n += 1
                    continue
                fn = contraction_kernel(
                    get_semiring(sr_name), pshape, part_shapes,
                    batched=True, bnb=use_bnb,
                    table_dtype=table_dtype,
                )
                args: List[Any]
                if table_dtype == "int8":
                    # mirror the stacked dispatch ABI: f32 dequant
                    # params (identity) prepended before the codes
                    np_ = len(part_shapes)
                    args = [
                        np.ones((h, np_), dtype=np.float32),
                        np.zeros((h, np_), dtype=np.float32),
                    ] + [
                        np.zeros((h,) + tuple(ps), dtype=np.int8)
                        for ps in part_shapes
                    ]
                else:
                    args = [
                        np.zeros(
                            (h,) + tuple(ps),
                            dtype=_np_table_dtype(table_dtype),
                        )
                        for ps in part_shapes
                    ]
                if use_bnb:
                    args.insert(
                        0, np.zeros((h,), dtype=np.float32)
                    )
                fn(*args)
                self._prewarmed.add((spec, h))
                n += 1
        return n

    def _prewarm_sparse(
        self, sr_name, pshape, part_lens, use_bnb, table_dtype, h
    ) -> None:
        """Compile one sparse candidate-bucket kernel at stack height
        ``h`` — mirrors ``ops/semiring.py:_dispatch_sparse``'s ABI
        (sep/own i32 rows, per-part packed values + gather indices,
        optional bnb budget and int8 dequant params)."""
        from pydcop_tpu.ops.sparse import (
            np_table_format_dtype,
            sparse_contraction_kernel,
        )

        n_cand_b, n_seg_b = pshape
        P = len(part_lens)
        fn = sparse_contraction_kernel(
            sr_name, n_cand_b, n_seg_b, tuple(part_lens),
            bnb=use_bnb, table_dtype=table_dtype,
        )
        sep = np.full((h, n_cand_b), n_seg_b, dtype=np.int32)
        own = np.zeros((h, n_cand_b), dtype=np.int32)
        vdt = np_table_format_dtype(table_dtype)
        args: List[Any] = [sep, own] + [
            np.zeros((h, int(L)), dtype=vdt) for L in part_lens
        ] + [
            np.zeros((h, n_cand_b), dtype=np.int32)
            for _ in part_lens
        ]
        if table_dtype == "int8":
            args = [
                np.ones((h, P), dtype=np.float32),
                np.zeros((h, P), dtype=np.float32),
            ] + args
        if use_bnb:
            args.insert(0, np.zeros((h,), dtype=np.float32))
        fn(*args)


class SweepMemoView:
    """One solve's window onto a :class:`SweepMemo`: lookups compare
    against THIS solve's fingerprints; stores record them."""

    __slots__ = ("memo", "fps", "hits", "stores")

    def __init__(self, memo: SweepMemo, fps: Dict[str, tuple]):
        self.memo = memo
        self.fps = fps
        self.hits = 0
        self.stores = 0

    def lookup(self, name: str):
        """The stored payload when the node's subtree fingerprint is
        unchanged, else None.  Does NOT count the hit — the sweep
        counts via :meth:`mark_hit` only once it decides the entry is
        reusable (bnb budget dominance can still reject it)."""
        fp = self.fps.get(name)
        if fp is None:
            return None
        return self.memo._get(name, fp)

    def mark_hit(self) -> None:
        self.hits += 1
        from pydcop_tpu.telemetry import get_metrics

        met = get_metrics()
        if met.enabled:
            met.inc("engine.memo_hits")

    def store(self, name: str, payload: Any) -> None:
        fp = self.fps.get(name)
        if fp is None:
            return
        self.stores += 1
        self.memo._put(name, fp, payload)
        from pydcop_tpu.telemetry import get_metrics

        met = get_metrics()
        if met.enabled:
            met.inc("engine.memo_recontractions")

    def note_kernel(
        self, sr_name, pshape, part_shapes, use_bnb,
        table_dtype="f32", table_format="dense",
    ):
        self.memo.note_kernel(
            sr_name, pshape, part_shapes, use_bnb, table_dtype,
            table_format,
        )


# -- fingerprint machinery ----------------------------------------------


def subtree_deps(
    names: Sequence[str],
    children: Mapping[str, Sequence[str]],
    own_deps: Mapping[str, set],
) -> Dict[str, Tuple[str, ...]]:
    """Per-node sorted tuple of external variables its SUBTREE depends
    on — the fingerprint key structure (fixed per session; only the
    values vary).  ``names`` lists parents before children (pre-order
    / reversed elimination order)."""
    deps: Dict[str, Tuple[str, ...]] = {}
    for n in reversed(list(names)):  # children before parents
        s = set(own_deps.get(n, ()))
        for c in children.get(n, ()):
            s.update(deps[c])
        deps[n] = tuple(sorted(s))
    return deps


def fingerprints(
    deps: Mapping[str, Tuple[str, ...]],
    ext_values: Mapping[str, Any],
) -> Dict[str, tuple]:
    """The per-node fingerprints at the given effective external
    values: a node with no subtree externals gets the empty tuple —
    a permanent hit after the cold solve; an A→B→A value flip
    re-hits the A entry (value-keyed, not version-keyed)."""
    return {
        n: tuple(repr(ext_values.get(e)) for e in d)
        for n, d in deps.items()
    }


def _ext_scope(dcop, cname: str) -> List[str]:
    ext = dcop.external_variables
    return [
        n for n in dcop.constraints[cname].scope_names if n in ext
    ]


def _clone_dcop(dcop):
    """A private copy whose externals the session may mutate (the
    session's effective values feed ``solution_cost``); sessions fed
    an unclonable in-process dcop fall back to mutating the shared
    object — the same values the caller streamed in, so the shared
    state stays consistent with the session."""
    import copy

    try:
        return copy.deepcopy(dcop)
    except Exception:  # noqa: BLE001 — exotic constraint closures
        return dcop


class ExactSession:
    """A pinned DPOP instance with memoized UTIL sweeps: ``solve``
    after ``set_values`` re-contracts only the dirty
    root-to-changed-constraint path (module docstring) and seeds the
    bnb incumbent from the previous solution so re-contracted nodes
    prune harder.

    ``memory_bound`` / ``max_util_bytes`` params route to the plain
    :func:`~pydcop_tpu.algorithms.dpop.solve_host` (their sweeps are
    dependent pass/lane sequences the per-node memo does not model).
    """

    def __init__(
        self,
        dcop,
        pad_policy: Any = None,
        memo_bytes: int = DEFAULT_MEMO_BYTES,
        clone: bool = True,
    ):
        from pydcop_tpu.algorithms import dpop as _dpop
        from pydcop_tpu.ops.padding import as_pad_policy

        self._dpop = _dpop
        self.dcop = _clone_dcop(dcop) if clone else dcop
        self.pad = as_pad_policy(pad_policy)
        self.sign = -1.0 if self.dcop.objective == "max" else 1.0
        prov: Dict[str, Tuple[str, int]] = {}
        (
            self.graph, self.domains, self.depth, self.owned,
        ) = _dpop._prepare_instance(self.dcop, provenance=prov)
        self.prov = prov
        self.cons_ext = {
            cn: _ext_scope(self.dcop, cn) for cn in prov
        }
        own: Dict[str, set] = {n: set() for n in self.domains}
        for cn, (owner, _i) in prov.items():
            own[owner].update(self.cons_ext[cn])
        self.names = [
            n
            for r in self.graph.roots
            for n in self.graph.depth_first_order(r)
        ]
        self.deps = subtree_deps(
            self.names,
            {
                n: list(self.graph.node(n).children)
                for n in self.names
            },
            own,
        )
        self.memo = SweepMemo(memo_bytes)
        self.seed: Optional[Dict[str, int]] = None
        self.solves = 0
        self.last_memo: Dict[str, int] = {}

    def set_values(self, values: Mapping[str, Any]) -> List[str]:
        """Apply external-variable deltas (a partial or full
        {external: value} map) and re-tabulate ONLY the touched
        constraints, in place.  Returns the touched constraint
        names."""
        evs = self.dcop.external_variables
        changed = []
        for name, val in values.items():
            ev = evs.get(name)
            if ev is None:
                raise ValueError(
                    f"set_values names {name!r} — not an external "
                    "variable of this session's dcop"
                )
            if ev.value != val:
                ev.value = val  # validates against the domain
                changed.append(name)
        if not changed:
            return []
        cs = set(changed)
        ext_now = {n: ev.value for n, ev in evs.items()}
        touched = [
            cn
            for cn in self.prov
            if cs.intersection(self.cons_ext[cn])
        ]
        for cn in touched:
            c = self.dcop.constraints[cn]
            c2 = c.slice(
                {e: ext_now[e] for e in self.cons_ext[cn]}
            )
            scope = list(c2.scope_names)
            table = self.sign * np.asarray(
                c2.as_matrix().matrix, dtype=np.float64
            )
            owner, idx = self.prov[cn]
            self.owned[owner][idx] = (scope, table)
        return touched

    def solve(
        self,
        params: Optional[Mapping[str, Any]] = None,
        timeout: Optional[float] = None,
        max_util_size: int = 1 << 26,
    ) -> Dict[str, Any]:
        t0 = time.perf_counter()
        _dpop = self._dpop
        params = dict(params or {})
        if (
            int(params.get("memory_bound", 0) or 0)
            or int(params.get("max_util_bytes", 0) or 0)
            # sparse solves run the planner sweep (unmemoized) —
            # algorithms/dpop.py routes them to ops/membound.py
            or params.get("table_format", "dense") != "dense"
        ):
            return _dpop.solve_host(
                self.dcop, params, timeout=timeout,
                max_util_size=max_util_size, pad_policy=self.pad,
            )
        dmc = _dpop._resolve_device_min_cells(params)
        level_sync = params.get("util_batch", "level") != "node"
        from pydcop_tpu.ops import semiring as _sr

        bnb = _sr.as_bnb(params.get("bnb"), "auto")
        table_dtype = _sr.as_table_dtype(params.get("table_dtype"))
        ext_now = {
            n: ev.value
            for n, ev in self.dcop.external_variables.items()
        }
        view = self.memo.begin(fingerprints(self.deps, ext_now))
        t_util = time.perf_counter()
        outs = _dpop._util_phase_multi(
            [
                _dpop._UtilInstance(
                    self.graph, self.domains, self.depth,
                    self.owned, dmc, bnb, view, self.seed,
                    table_dtype,
                )
            ],
            t0, timeout, max_util_size=max_util_size,
            pad=self.pad, level_sync=level_sync,
        )
        if outs is None:
            return _dpop._timeout_result(self.dcop, t0)
        (best_choice, cells, dev_nodes, host_nodes,
         dispatches) = outs[0]
        assignment = _dpop._value_phase(
            self.graph, self.domains, best_choice
        )
        result = _dpop._assemble_result(
            self.dcop, self.graph, self.domains, self.depth,
            assignment,
            {
                "util_time": time.perf_counter() - t_util,
                "util_backend": (
                    "device" if dmc is not None else "host"
                ),
                "util_cells": cells,
                "util_device_nodes": dev_nodes,
                "util_host_nodes": host_nodes,
                "util_dispatches": dispatches,
            },
            t0, 1,
        )
        self.last_memo = {
            "nodes": len(self.names),
            "hits": view.hits if view is not None else 0,
            "recontracted": (
                view.stores if view is not None else len(self.names)
            ),
            "evictions": self.memo.evictions,
        }
        result["memo"] = dict(self.last_memo)
        # the next solve's bnb incumbent: this solution re-evaluated
        # under the post-delta tables is a valid bound (it IS an
        # assignment), and usually a near-optimal one
        self.seed = {
            n: self.domains[n].index(v)
            for n, v in assignment.items()
        }
        self.solves += 1
        # compile the 1-row stacked variants of every kernel this
        # sweep used — the warm path's zero-XLA-compile guarantee
        self.memo.prewarm()
        return result


class InferSession:
    """A pinned inference instance (``ops/semiring.py``) with
    memoized contraction sweeps — ``map`` / ``log_z`` / ``marginals``
    / ``kbest:<k>`` follow-ups after ``set_values`` re-contract only
    the dirty path.  BnB-pruned instances run UNMEMOIZED sweeps (a
    budget-pruned message depends on the global incumbent, not just
    the subtree — ``contract_sweep`` drops the memo when it builds a
    pruning context for the instance)."""

    def __init__(
        self,
        dcop,
        query: str,
        *,
        order: str = "pseudo_tree",
        beta: float = 1.0,
        tol: float = 1e-6,
        device: str = "auto",
        device_min_cells: int = 1 << 14,
        pad_policy: Any = None,
        max_table_size: int = 1 << 26,
        bnb: str = "auto",
        table_dtype: str = "f32",
        table_format: str = "dense",
        memo_bytes: int = DEFAULT_MEMO_BYTES,
        clone: bool = True,
    ):
        from pydcop_tpu.ops import semiring as _sr
        from pydcop_tpu.ops.sparse import as_table_format as _as_fmt

        self._sr = _sr
        qkind, _ = _sr.parse_query(query)
        if qkind in ("marginal_map", "expectation"):
            raise ValueError(
                f"query {query!r} has no memoized session path — "
                "its plan carries query-specific structure "
                "(map_vars / external distributions); use "
                "api.infer per call"
            )
        self.dcop = _clone_dcop(dcop) if clone else dcop
        self.query = query
        self.kw = dict(
            order=order, beta=beta, tol=tol, device=device,
            device_min_cells=device_min_cells,
            pad_policy=pad_policy, max_table_size=max_table_size,
            bnb=bnb,
            table_dtype=_sr.as_table_dtype(table_dtype),
            table_format=_as_fmt(table_format),
        )
        self.sign = -1.0 if self.dcop.objective == "max" else 1.0
        prov: Dict[str, Any] = {}
        self.plan = _sr.build_plan(
            self.dcop, order=order, provenance=prov
        )
        self.prov = prov
        self.cons_ext = {
            cn: _ext_scope(self.dcop, cn) for cn in prov
        }
        # fully-external constraints fold into const_energy — track
        # their identities so a delta re-folds the constant exactly
        self.const_cons = [
            cn for cn, p in prov.items() if p[0] == "const"
        ]
        self.base_const = self.plan.const_energy - sum(
            self._const_val(cn) for cn in self.const_cons
        )
        own: Dict[str, set] = {
            n: set() for n in self.plan.order
        }
        for cn, p in prov.items():
            if p[0] != "const":
                own[p[0]].update(self.cons_ext[cn])
        self.deps = subtree_deps(
            list(reversed(self.plan.order)),  # parents first
            self.plan.children, own,
        )
        self.memo = SweepMemo(memo_bytes)
        self.solves = 0
        self.last_memo: Dict[str, int] = {}

    def _const_val(self, cn: str) -> float:
        evs = self.dcop.external_variables
        c = self.dcop.constraints[cn]
        c2 = c.slice(
            {
                e: evs[e].value
                for e in c.scope_names
                if e in evs
            }
        )
        return self.sign * float(
            np.asarray(c2.as_matrix().matrix, dtype=np.float64)
        )

    def set_values(self, values: Mapping[str, Any]) -> List[str]:
        evs = self.dcop.external_variables
        changed = []
        for name, val in values.items():
            ev = evs.get(name)
            if ev is None:
                raise ValueError(
                    f"set_values names {name!r} — not an external "
                    "variable of this session's dcop"
                )
            if ev.value != val:
                ev.value = val
                changed.append(name)
        if not changed:
            return []
        cs = set(changed)
        ext_now = {n: ev.value for n, ev in evs.items()}
        touched = [
            cn
            for cn in self.prov
            if cs.intersection(self.cons_ext[cn])
        ]
        refold = False
        for cn in touched:
            kind = self.prov[cn][0]
            if kind == "const":
                refold = True
                continue
            owner, idx = self.prov[cn]
            c = self.dcop.constraints[cn]
            c2 = c.slice(
                {e: ext_now[e] for e in self.cons_ext[cn]}
            )
            scope = list(c2.scope_names)
            table = self.sign * np.asarray(
                c2.as_matrix().matrix, dtype=np.float64
            )
            self.plan.buckets[owner][idx] = (scope, table)
        if refold:
            self.plan.const_energy = self.base_const + sum(
                self._const_val(cn) for cn in self.const_cons
            )
        return touched

    def solve(
        self, timeout: Optional[float] = None
    ) -> Dict[str, Any]:
        ext_now = {
            n: ev.value
            for n, ev in self.dcop.external_variables.items()
        }
        view = self.memo.begin(fingerprints(self.deps, ext_now))
        out = self._sr.run_infer_many(
            [self.dcop], self.query, timeout=timeout,
            _plans=[self.plan], _memos=[view], **self.kw
        )[0]
        self.last_memo = {
            "nodes": len(self.plan.order),
            "hits": view.hits if view is not None else 0,
            "recontracted": (
                view.stores
                if view is not None
                else len(self.plan.order)
            ),
            "evictions": self.memo.evictions,
        }
        out["memo"] = dict(self.last_memo)
        self.solves += 1
        self.memo.prewarm()
        return out
