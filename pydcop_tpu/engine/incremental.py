"""Incremental problem recompilation for dynamic runs.

The dynamic engine (``engine/dynamic.py``) solves a *sequence* of
closely-related problems: each scenario event perturbs the active DCOP
(an external variable changes value, a lost variable freezes into an
external) and the next segment solves the perturbed problem.  The naive
path re-tabulates every constraint and rebuilds the whole
:class:`~pydcop_tpu.ops.compile.CompiledProblem` on the host per
segment — ~seconds of Python/numpy work per event on large problems,
plus a device→host pull to fingerprint the result.

:class:`IncrementalCompiler` removes that cost for the common cases:

- **Nothing changed** (delay events): the cached compiled problem and
  fingerprint are returned as-is — zero host work, zero transfers.
- **Only external VALUES changed** (``set_value`` events): the problem
  STRUCTURE (variables, scopes, shapes, static metadata) is unchanged,
  so only the constraints whose scope touches a changed external are
  re-tabulated, and their slices of ``tables_flat`` / the arity-bucket
  tables / the folded ``unary`` rows are delta-updated ON DEVICE with
  ``.at[].set``/``.add``.  The resulting problem shares every static
  field with its predecessor, so the engine's jitted chunk runners hit
  the trace cache — a segment transition costs a few small device
  updates instead of a host rebuild + trace + XLA compile.
- **Structure changed** (a variable froze, the frozen set changed): a
  full recompile, after which the edit plan is rebuilt.  With a
  ``pad_policy`` the recompiled arrays usually land in the same shape
  buckets, so even this path reuses the compiled executables (see
  ``ops/padding.py`` and ``docs/performance.md``).

Fingerprints: full compiles hash the compiled arrays
(:func:`~pydcop_tpu.ops.compile.problem_fingerprint`); incremental
updates derive the fingerprint from the base hash + the *effective*
external values (those actually read by some constraint), so delay
segments and no-op ``set_value`` events keep the fingerprint stable and
the engine's full-state carry intact.

Telemetry counters (``docs/observability.md``): ``compile.full``,
``compile.incremental``, ``compile.reused``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Any, Dict, List, Mapping, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import ExternalVariable
from pydcop_tpu.ops.compile import (
    CompiledProblem,
    _tabulate_padded,
    compile_dcop,
    problem_fingerprint,
)


class IncrementalCompiler:
    """Compile the active problem of a dynamic run, reusing work
    across segments (see module docstring).

    ``compile(frozen, ext_overrides)`` returns ``(problem,
    fingerprint)`` for the current run state, or ``(None, None)`` when
    every variable is frozen/external.  The returned problem must be
    treated as immutable (the engine's ``dataclasses.replace`` for
    initial values is fine — it never mutates the cached arrays).
    """

    def __init__(
        self,
        dcop: DCOP,
        n_shards: int = 1,
        pad_policy="none",
        dtype=jnp.float32,
    ):
        self.dcop = dcop
        self.n_shards = n_shards
        self.pad_policy = pad_policy
        self.dtype = dtype
        self._sign = -1.0 if dcop.objective == "max" else 1.0
        self._frozen_key: Optional[frozenset] = None
        self._problem: Optional[CompiledProblem] = None
        self._base_fp: Optional[str] = None
        self._fp: Optional[str] = None
        self._ext_state: Dict[str, Any] = {}
        # edit plan: per tracked constraint name, how its current
        # realization lands in the compiled arrays
        self._plan: Dict[str, Dict[str, Any]] = {}
        self._ext_to_cons: Dict[str, List[str]] = {}
        # incremental updates need the single-shard arity-major layout
        # and per-constraint (non-shared) tables
        self._incremental_ok = False

    # -- public --------------------------------------------------------

    def compile(
        self,
        frozen: Mapping[str, Any],
        ext_overrides: Mapping[str, Any],
    ) -> Tuple[Optional[CompiledProblem], Optional[str]]:
        from pydcop_tpu.telemetry import get_metrics, get_tracer

        met = get_metrics()
        ext_values = {
            name: ext_overrides.get(name, ev.value)
            for name, ev in self.dcop.external_variables.items()
        }
        fkey = frozenset(frozen.items())
        if self._problem is not None and fkey == self._frozen_key:
            changed = {
                n
                for n, v in ext_values.items()
                if self._ext_state.get(n) != v
            }
            if not changed:
                if met.enabled:
                    met.inc("compile.reused")
                return self._problem, self._fp
            if self._incremental_ok:
                affected = sorted(
                    {
                        cn
                        for e in changed
                        for cn in self._ext_to_cons.get(e, ())
                    }
                )
                if not affected:
                    # the changed externals feed no compiled
                    # constraint (fully-external ones are dropped by
                    # the compiler): arrays and fingerprint are
                    # untouched — a pure reuse, and the state carry
                    # survives
                    self._ext_state = ext_values
                    if met.enabled:
                        met.inc("compile.reused")
                    return self._problem, self._fp
                t0 = time.perf_counter()
                n_updates = self._apply_updates(
                    affected, {**ext_values, **frozen}
                )
                self._ext_state = ext_values
                self._fp = self._fingerprint(ext_values)
                if met.enabled:
                    met.inc("compile.incremental")
                tr = get_tracer()
                if tr.enabled:
                    tr.add_span(
                        "incremental-update", "compile", t0,
                        time.perf_counter() - t0,
                        constraints=n_updates,
                    )
                return self._problem, self._fp
        # structure changed (or first call, or incremental unsupported):
        # full rebuild
        problem = self._full_compile(frozen, ext_values)
        if problem is None:
            return None, None
        if met.enabled:
            met.inc("compile.full")
        return self._problem, self._fp

    # -- full compile + plan build -------------------------------------

    def _active_dcop(
        self, frozen: Mapping[str, Any], ext_values: Mapping[str, Any]
    ) -> DCOP:
        """The currently-solvable problem: frozen variables become
        external (constant at their last value), external overrides
        applied."""
        d = DCOP(self.dcop.name, objective=self.dcop.objective)
        for v in self.dcop.variables.values():
            if v.name in frozen:
                d.add_variable(
                    ExternalVariable(v.name, v.domain, frozen[v.name])
                )
            else:
                d.add_variable(v)
        for ev in self.dcop.external_variables.values():
            d.add_variable(
                ExternalVariable(ev.name, ev.domain, ext_values[ev.name])
            )
        for c in self.dcop.constraints.values():
            d.add_constraint(c)
        return d

    def _full_compile(
        self, frozen: Mapping[str, Any], ext_values: Dict[str, Any]
    ) -> Optional[CompiledProblem]:
        ad = self._active_dcop(frozen, ext_values)
        if not ad.variables:
            # everything frozen/external: nothing to solve
            self._problem = None
            self._frozen_key = None
            return None
        problem = compile_dcop(
            ad,
            dtype=self.dtype,
            n_shards=self.n_shards,
            pad_policy=self.pad_policy,
        )
        self._problem = problem
        self._frozen_key = frozenset(frozen.items())
        self._ext_state = dict(ext_values)
        self._base_fp = problem_fingerprint(problem)
        self._build_plan(problem, frozen, ext_values)
        self._fp = self._fingerprint(ext_values)
        return problem

    def _build_plan(
        self,
        problem: CompiledProblem,
        frozen: Mapping[str, Any],
        ext_values: Mapping[str, Any],
    ) -> None:
        """Record, for every constraint touching a DECLARED external
        variable, where its current realization lives in the compiled
        arrays.  Frozen variables never change value within a
        structure, so frozen-only constraints are static here."""
        self._plan = {}
        self._ext_to_cons = {}
        self._incremental_ok = self.n_shards <= 1 and not any(
            b.shared_table for b in problem.buckets.values()
        )
        if not self._incremental_ok:
            return
        declared = set(self.dcop.external_variables)
        full_ext = {**ext_values, **frozen}
        d_max = problem.d_max
        con_idx = {name: i for i, name in enumerate(problem.con_names)}
        # arity-major layout: bucket row of constraint ci with arity k
        # is ci - (index of the first arity-k constraint)
        arity_base: Dict[int, int] = {}
        base = 0
        for k in sorted(problem.buckets):
            arity_base[k] = base
            base += problem.buckets[k].n_cons
        con_offset = np.asarray(problem.con_offset)
        var_slot = {
            name: i
            for i, name in enumerate(
                problem.var_names[: problem.n_real_vars]
            )
        }
        domain_sizes = np.asarray(problem.domain_sizes)

        for cname, c in self.dcop.constraints.items():
            scope = list(c.scope_names)
            hot = [n for n in scope if n in declared]
            if not hot:
                continue
            scope_ext = [n for n in scope if n in full_ext]
            live = [n for n in scope if n not in full_ext]
            entry: Dict[str, Any] = {"ext": scope_ext}
            if not live:
                # fully-external constraint: the compiler drops it, so
                # its externals never touch the compiled arrays — keep
                # it OUT of _ext_to_cons or a set_value on one would
                # churn the fingerprint (and drop the state carry)
                # over byte-identical arrays
                continue
            elif len(live) == 1:
                slot = var_slot[live[0]]
                entry["kind"] = "unary"
                entry["slot"] = slot
                entry["dlen"] = int(domain_sizes[slot])
                entry["table"] = self._tabulate(c, scope_ext, full_ext, d_max)
            else:
                ci = con_idx[cname]
                k = len(live)
                entry["kind"] = "multi"
                entry["ci"] = ci
                entry["k"] = k
                entry["offset"] = int(con_offset[ci])
                entry["row"] = ci - arity_base[k]
            self._plan[cname] = entry
            for n in hot:
                self._ext_to_cons.setdefault(n, []).append(cname)

    # -- incremental update --------------------------------------------

    def _tabulate(
        self, c, scope_ext, full_ext: Mapping[str, Any], d_max: int
    ) -> np.ndarray:
        sliced = c.slice({n: full_ext[n] for n in scope_ext})
        return _tabulate_padded(sliced, d_max) * self._sign

    def _apply_updates(
        self, names: List[str], full_ext: Dict[str, Any]
    ) -> int:
        p = self._problem
        d_max = p.d_max
        # accumulate all edits on host, then issue ONE batched update
        # per device array — eager per-constraint .at ops would copy
        # each (potentially huge) array once per touched constraint
        flat_idx: List[np.ndarray] = []
        flat_val: List[np.ndarray] = []
        unary_slots: List[int] = []
        unary_deltas: List[np.ndarray] = []
        brow_updates: Dict[int, Tuple[List[int], List[np.ndarray]]] = {}
        n_updates = 0
        for cname in names:
            entry = self._plan[cname]
            c = self.dcop.constraints[cname]
            tbl = self._tabulate(c, entry["ext"], full_ext, d_max)
            n_updates += 1
            if entry["kind"] == "unary":
                dlen = entry["dlen"]
                delta = np.zeros(d_max, dtype=np.float32)
                delta[:dlen] = tbl[:dlen] - entry["table"][:dlen]
                unary_slots.append(entry["slot"])
                unary_deltas.append(delta)
                entry["table"] = tbl
            else:
                size = d_max ** entry["k"]
                flat_idx.append(
                    np.arange(
                        entry["offset"],
                        entry["offset"] + size,
                        dtype=np.int32,
                    )
                )
                flat_val.append(tbl.reshape(-1))
                rows, tbls = brow_updates.setdefault(
                    entry["k"], ([], [])
                )
                rows.append(entry["row"])
                tbls.append(tbl)

        tables_flat = p.tables_flat
        unary = p.unary
        if flat_idx:
            tables_flat = tables_flat.at[
                jnp.asarray(np.concatenate(flat_idx))
            ].set(
                jnp.asarray(
                    np.concatenate(flat_val), dtype=tables_flat.dtype
                )
            )
        if unary_slots:
            # .add with duplicate slot indices accumulates, so several
            # updated constraints folding into one variable compose
            unary = unary.at[jnp.asarray(unary_slots)].add(
                jnp.asarray(np.stack(unary_deltas), dtype=unary.dtype)
            )
        buckets = dict(p.buckets)
        for k, (rows, tbls) in brow_updates.items():
            b = buckets[k]
            stack = np.stack(tbls)
            rows_a = jnp.asarray(np.asarray(rows, dtype=np.int32))
            buckets[k] = dataclasses.replace(
                b,
                tables=b.tables.at[rows_a].set(
                    jnp.asarray(stack, dtype=b.tables.dtype)
                ),
                tables_t=b.tables_t.at[..., rows_a].set(
                    jnp.asarray(
                        np.moveaxis(stack, 0, -1),
                        dtype=b.tables_t.dtype,
                    )
                ),
            )
        self._problem = dataclasses.replace(
            p, unary=unary, tables_flat=tables_flat, buckets=buckets
        )
        return n_updates

    # -- fingerprint ---------------------------------------------------

    def _fingerprint(self, ext_values: Mapping[str, Any]) -> str:
        """Stable id of the current problem CONTENT: the base compile's
        array hash + the effective external values.  Externals no
        constraint reads are excluded, so changing them never breaks
        the engine's full-state carry."""
        effective = sorted(
            (n, v)
            for n, v in ext_values.items()
            if n in self._ext_to_cons
        )
        h = hashlib.sha256(self._base_fp.encode())
        h.update(repr(effective).encode())
        return h.hexdigest()[:16]
