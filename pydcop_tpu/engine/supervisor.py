"""Supervised device execution: the failure-classification and
recovery seam every engine dispatch routes through.

PR 1 made the *message planes* fault-tolerant; the device engine that
carries almost all the work — the ``engine/batched.py`` chunk
runners, ``run_many_batched`` vmapped instance groups, the DPOP
level-synchronous UTIL sweeps — still failed whole calls on the first
transient XLA error, HBM exhaustion, or a single NaN-poisoned
instance.  This module is the device analogue of the message plane's
chaos/backoff stack: one :class:`Supervisor` wraps every device
dispatch and

- **classifies failures** (:func:`classify_failure`): transient
  runtime errors retry in place with the shared deterministic
  keyed-jitter backoff (``utils/backoff.py``) under a per-call
  ``retry_budget``; ``RESOURCE_EXHAUSTED``/OOM surfaces as
  :class:`DeviceOOMError` so the *caller* can degrade adaptively
  (``run_batched`` halves its chunk size down to ``chunk_floor``;
  ``run_many_batched`` splits the vmapped instance group and
  re-dispatches the halves — stream-preserving, so results stay
  bit-identical — and DPOP splits a level stack, falling back to the
  exact host f64 join when even a single row won't fit); everything
  else is unrecoverable and surfaces with full telemetry context
  (engines write a final checkpoint first when one is configured);
- **hosts the injection seam** for the seeded device-layer fault
  kinds (``device_oom``, ``device_transient``, ``nan_inject`` —
  ``pydcop_tpu.faults.plan.DeviceFaults``): injected faults fire
  BEFORE the wrapped call, deterministically per ``(plan seed, scope,
  sequence number)``, under the same ``--chaos SPEC --chaos_seed N``
  contract as the message-plane chaos layer — so every recovery path
  above is testable on demand (``tests/test_supervisor.py``,
  ``tools/recompile_guard.py:run_supervisor_guard``);
- **screens numeric faults**: engines hand each chunk boundary's cost
  samples to :meth:`Supervisor.nan_lanes`/``numpy.isnan`` screens and
  quarantine only the poisoned instances out of a ``solve_many``
  group (``on_numeric_fault='quarantine'``: the lane finishes with
  ``status="degraded"`` carrying its last-finite anytime best, the
  other K−1 lanes are untouched and bit-identical;
  ``'raise'``: the whole call fails).  Only NaN is treated as poison:
  ``±inf`` is a legitimate cost for hard-constraint tables, NaN never
  is.

Telemetry: counters ``engine.retries``, ``engine.oom_splits``,
``engine.oom_chunk_halvings``, ``engine.quarantined_instances``,
``engine.numeric_faults`` plus ``fault.device_oom`` /
``fault.device_transient`` / ``fault.nan_inject`` per injected fault,
and ``supervisor``-category trace events for every recovery action —
all landing in ``result["telemetry"]`` (``docs/faults.md`` has the
fault → action → status/counter recovery matrix).

This module is deliberately jax-free (classification is by exception
type name + status-code markers), so the host-path engines
(``engine/host_batch.py``, pure-host DPOP/SyncBB) stay importable
without the jax import chain.

The active supervisor is ambient (:func:`get_supervisor` /
:func:`supervision`), like the telemetry session: ``api.solve`` /
``api.solve_many`` install one per call from the ``retry_budget``,
``chunk_floor``, ``on_numeric_fault`` and ``chaos`` knobs, and every
engine layer underneath — including DPOP level sweeps reached through
``solve_host_many`` and dynamic-run segments reached through
``run_batched`` — picks it up without signature plumbing.  With no
session-scoped supervisor installed, a process-default one (retries
on, no injection) supervises every dispatch.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional

from pydcop_tpu.telemetry import get_metrics, get_tracer
from pydcop_tpu.utils.backoff import backoff_delays


class DeviceOOMError(RuntimeError):
    """A device dispatch exhausted accelerator memory (real
    ``RESOURCE_EXHAUSTED`` or injected ``device_oom``).  Engines catch
    this and degrade — halve the chunk, split the group — instead of
    failing the call.

    ``injected`` distinguishes the chaos plan's capacity model (fires
    BEFORE the wrapped call, so the caller's carry buffers are
    untouched) from a real allocation failure surfacing at the sync
    point (a donated dispatch has already consumed its carries —
    in-place re-dispatch would touch deleted buffers)."""

    def __init__(self, message: str, *, injected: bool = False):
        super().__init__(message)
        self.injected = injected


class DeviceTransientError(RuntimeError):
    """An injected transient device failure (``device_transient``) —
    the scripted analogue of a flaky XLA ``UNAVAILABLE``/``INTERNAL``
    runtime error."""


class UnrecoverableDeviceError(RuntimeError):
    """A supervised dispatch that could not be saved: the transient
    retry budget is exhausted, the OOM degradation ladder bottomed
    out (chunk at floor / single-lane dispatch still over capacity),
    or an instance went numerically poisoned under
    ``on_numeric_fault='raise'``.  Carries the dispatch context the
    postmortem needs; engines write a final checkpoint before letting
    it surface when one is configured."""

    def __init__(
        self,
        message: str,
        *,
        scope: Optional[str] = None,
        kind: str = "fatal",
        attempts: int = 0,
    ):
        super().__init__(message)
        self.scope = scope
        self.kind = kind  # 'transient' | 'oom' | 'numeric' | 'fatal'
        self.attempts = attempts


# status-code / message markers for classification.  OOM is checked
# first: an XLA allocation failure often carries both RESOURCE_
# EXHAUSTED and INTERNAL-looking text, and retrying an OOM verbatim
# is pointless — degradation is the only move that changes anything.
_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "RESOURCE EXHAUSTED",
    "Out of memory",
    "out of memory",
    "OOM",
    "Failed to allocate",
    "failed to allocate",
)
_TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "CANCELLED",
    "DATA_LOSS",
    "INTERNAL",
    "Socket closed",
    "connection reset",
)


def classify_failure(exc: BaseException) -> str:
    """``'oom'`` | ``'transient'`` | ``'fatal'``.

    Classification is by exception type NAME plus status-code markers
    in the message — never by importing jax types, so this module
    stays importable on the jax-free host paths.  Python-level usage
    errors (``ValueError``, ``TypeError``, shape mismatches raised at
    trace time) classify fatal: retrying a bug never fixes it."""
    if isinstance(exc, DeviceOOMError):
        return "oom"
    if isinstance(exc, DeviceTransientError):
        return "transient"
    if isinstance(exc, MemoryError):
        return "oom"
    text = f"{type(exc).__name__}: {exc}"
    if any(m in text for m in _OOM_MARKERS):
        return "oom"
    if any(m in text for m in _TRANSIENT_MARKERS):
        return "transient"
    return "fatal"


@dataclass
class SupervisorConfig:
    """Knobs of one supervised call (``api.solve(retry_budget=...,
    chunk_floor=..., on_numeric_fault=...)`` / the solve/run/batch
    CLI flags).

    ``retry_budget`` bounds transient retries PER DISPATCH (0 turns
    retry off).  ``chunk_floor`` is the smallest chunk size the OOM
    degradation ladder may halve down to — the ``max_util_bytes``-
    style floor below which a run is declared genuinely over
    capacity.  ``on_numeric_fault`` picks quarantine (degrade only
    the poisoned instances) or raise (fail the call).  ``plan`` is a
    :class:`~pydcop_tpu.faults.plan.FaultPlan` whose device-layer
    kinds inject at this seam; its seed also keys the deterministic
    retry-backoff jitter so chaos replays reproduce retry timing
    exactly."""

    retry_budget: int = 2
    chunk_floor: int = 8
    on_numeric_fault: str = "quarantine"  # 'quarantine' | 'raise'
    backoff_base: float = 0.02
    backoff_factor: float = 2.0
    backoff_max: float = 0.25
    backoff_jitter: float = 0.25
    plan: Optional[Any] = None  # FaultPlan (device-layer kinds)
    sleep: Callable[[float], None] = field(default=time.sleep)

    def __post_init__(self) -> None:
        if self.retry_budget < 0:
            raise ValueError(
                f"retry_budget must be >= 0, got {self.retry_budget}"
            )
        if self.chunk_floor < 1:
            raise ValueError(
                f"chunk_floor must be >= 1, got {self.chunk_floor}"
            )
        if self.on_numeric_fault not in ("quarantine", "raise"):
            raise ValueError(
                "on_numeric_fault must be 'quarantine' or 'raise', "
                f"got {self.on_numeric_fault!r}"
            )


class Supervisor:
    """Supervised dispatch wrapper (module docstring).

    Dispatch sequence numbers are per-scope and deterministic (device
    calls are issued in a deterministic order by every engine), which
    is what makes the injected fault schedule replayable: fault
    decisions are pure in ``(plan seed, scope, seq)``.
    """

    #: real supervisor — engines run injection + numeric screening.
    #: (:data:`UNSUPERVISED` flips this off for the bench baseline.)
    active = True

    def __init__(self, config: Optional[SupervisorConfig] = None):
        self.config = config or SupervisorConfig()
        self._seq: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- knob accessors the engines read --------------------------------

    @property
    def plan(self):
        return self.config.plan

    @property
    def chunk_floor(self) -> int:
        return self.config.chunk_floor

    @property
    def on_numeric_fault(self) -> str:
        return self.config.on_numeric_fault

    # -- internals -------------------------------------------------------

    def _next_seq(self, scope: str) -> int:
        with self._lock:
            s = self._seq[scope] = self._seq.get(scope, 0) + 1
        return s

    def _record_fault(self, kind: str, scope: str, seq: int) -> None:
        """Injected faults land on the run's telemetry exactly like
        the message-plane chaos layer's: ``fault.<kind>`` counters and
        ``fault``-category events carrying scope/seq/seed."""
        met = get_metrics()
        if met.enabled:
            met.inc(f"fault.{kind}")
        tr = get_tracer()
        if tr.enabled:
            tr.event(
                kind, cat="fault", link=scope, seq=seq,
                seed=self.config.plan.seed if self.config.plan else None,
            )

    def _inject(
        self,
        scope: str,
        seq: int,
        width: int,
        rounds: Optional[int],
        table_bytes: Optional[int] = None,
    ) -> None:
        plan = self.config.plan
        if plan is None or not plan.device_faults_configured:
            return
        if plan.oom_injected(width, rounds, table_bytes):
            self._record_fault("device_oom", scope, seq)
            raise DeviceOOMError(
                f"injected device OOM: dispatch {scope}#{seq} "
                f"(width={width}, rounds={rounds}, "
                f"table_bytes={table_bytes}) exceeds the chaos "
                "plan's capacity",
                injected=True,
            )
        if plan.decide_device_transient(scope, seq):
            self._record_fault("device_transient", scope, seq)
            raise DeviceTransientError(
                f"injected transient device failure: {scope}#{seq}"
            )

    # -- the dispatch seam -----------------------------------------------

    def dispatch(
        self,
        fn: Callable[[], Any],
        *,
        scope: str = "engine.chunk",
        width: int = 1,
        rounds: Optional[int] = None,
        retryable: bool = True,
        table_bytes: Optional[int] = None,
    ):
        """Run one device dispatch under supervision and return its
        result.

        ``fn`` must be a zero-arg closure that runs the device call
        AND forces its outputs to host (``np.asarray``) — with jax's
        async dispatch, a runtime failure only surfaces at the sync
        point, and it must surface HERE to be classified.  ``width``
        is the dispatch's vmapped lane count (instances × restarts,
        or a DPOP stack height) and ``rounds`` its scanned round
        count — the quantities the injected capacity model and the
        callers' degradation moves operate on.  ``table_bytes`` is
        the dispatch's PER-LANE joined-table size (the UTIL-sweep
        quantity exponential in induced width) — the dimension the
        ``device_oom_bytes`` capacity model caps and the budgeted
        sweeps' replan ladder shrinks (``ops/membound.py``).

        Transient failures retry in place (seeded keyed-jitter
        backoff, ``engine.retries``) up to ``retry_budget`` times,
        then surface as :class:`UnrecoverableDeviceError`.  OOM —
        real or injected — always surfaces as
        :class:`DeviceOOMError` for the caller's degradation ladder:
        retrying the identical dispatch cannot un-exhaust memory.
        Fatal failures re-raise UNWRAPPED (the original type is the
        diagnosis) after a telemetry event.

        ``retryable=False`` says ``fn`` must NOT be called again
        after a REAL failure: a dispatch that donates its carry
        buffers (``run_many_batched`` with ``donate=True``) has
        already consumed its inputs by the time the failure surfaces
        at the sync point, so an in-place replay would touch deleted
        buffers.  Real transients then surface as
        :class:`DeviceTransientError` for a caller-level restart
        (which owns the retry budget for that path).  Injected
        faults fire BEFORE the wrapped call runs — carries untouched
        — so they retry in place regardless.
        """
        cfg = self.config
        met = get_metrics()
        tr = get_tracer()
        attempts = 0
        delays: Optional[Iterator[float]] = None

        def _backoff(seq: int) -> None:
            nonlocal attempts, delays
            attempts += 1
            if met.enabled:
                met.inc("engine.retries")
            if tr.enabled:
                tr.event(
                    "retry", cat="supervisor", scope=scope,
                    seq=seq, attempt=attempts,
                )
            if delays is None:
                delays = backoff_delays(
                    base=cfg.backoff_base,
                    factor=cfg.backoff_factor,
                    max_delay=cfg.backoff_max,
                    jitter=cfg.backoff_jitter,
                    seed=(
                        cfg.plan.seed if cfg.plan is not None else 0
                    ),
                    key=f"supervisor:{scope}",
                )
            cfg.sleep(next(delays))

        def _exhausted(seq: int, e: BaseException) -> None:
            if tr.enabled:
                tr.event(
                    "retry-exhausted", cat="supervisor",
                    scope=scope, seq=seq, attempts=attempts,
                )
            raise UnrecoverableDeviceError(
                f"{scope}: transient device failure persisted "
                f"through the retry budget "
                f"({cfg.retry_budget}): {e}",
                scope=scope, kind="transient", attempts=attempts,
            ) from e

        while True:
            seq = self._next_seq(scope)
            try:
                self._inject(scope, seq, width, rounds, table_bytes)
            except DeviceTransientError as e:
                # injected BEFORE fn ran: in-place retry is sound
                # even for donated dispatches
                if attempts < cfg.retry_budget:
                    _backoff(seq)
                    continue
                _exhausted(seq, e)
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — classified below
                kind = classify_failure(e)
                if kind == "oom":
                    if isinstance(e, DeviceOOMError):
                        raise
                    raise DeviceOOMError(f"{scope}: {e}") from e
                if kind == "transient":
                    if retryable and attempts < cfg.retry_budget:
                        _backoff(seq)
                        continue
                    if not retryable:
                        # hand the transient back for a caller-level
                        # restart — fn's inputs may be consumed
                        if isinstance(e, DeviceTransientError):
                            raise
                        raise DeviceTransientError(
                            f"{scope}: {e}"
                        ) from e
                    _exhausted(seq, e)
                if tr.enabled:
                    tr.event(
                        "fatal", cat="supervisor", scope=scope,
                        seq=seq, error=str(e)[:200],
                    )
                raise  # fatal: the original exception IS the report

    # -- numeric-fault injection (the nan_inject seam) -------------------

    def nan_lanes(self, n_lanes: int, scope: str = "engine.chunk") -> List[int]:
        """Stack lanes whose carry the chaos plan poisons at this
        chunk boundary (empty without a plan).  Boundary sequence
        numbers are per-scope, so the schedule is replayable."""
        plan = self.config.plan
        if plan is None or not plan.device.nan:
            return []
        seq = self._next_seq(f"nan:{scope}")
        lanes = [
            i for i in range(n_lanes) if plan.decide_nan_inject(i, seq)
        ]
        for i in lanes:
            self._record_fault("nan_inject", f"{scope}[{i}]", seq)
        return lanes


class _Unsupervised:
    """Bare dispatch — no classification, no retry, no injection, no
    numeric screening.  The measured baseline of the bench's
    ``supervised_overhead`` stage; never the default."""

    active = False
    plan = None
    chunk_floor = 1
    on_numeric_fault = "quarantine"

    def dispatch(self, fn, **_kw):
        return fn()

    def nan_lanes(self, n_lanes, scope="engine.chunk"):
        return []


UNSUPERVISED = _Unsupervised()

_ACTIVE: Optional[Supervisor] = None
_DEFAULT: Optional[Supervisor] = None
_DEFAULT_LOCK = threading.Lock()


def make_supervisor(
    retry_budget: Optional[int] = None,
    chunk_floor: Optional[int] = None,
    on_numeric_fault: Optional[str] = None,
    plan: Optional[Any] = None,
) -> Supervisor:
    """Build a per-call :class:`Supervisor` from optional knobs —
    ``None`` means "use the :class:`SupervisorConfig` default", so the
    dataclass stays the single place those defaults live (the api /
    CLI entry points all construct through here)."""
    knobs = {
        "retry_budget": retry_budget,
        "chunk_floor": chunk_floor,
        "on_numeric_fault": on_numeric_fault,
    }
    return Supervisor(
        SupervisorConfig(
            plan=plan,
            **{k: v for k, v in knobs.items() if v is not None},
        )
    )


def get_supervisor() -> Supervisor:
    """The ambient supervisor: the one :func:`supervision` installed,
    else a process-default (retries on, no injection)."""
    sup = _ACTIVE
    if sup is not None:
        return sup
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = Supervisor()
    return _DEFAULT


@contextlib.contextmanager
def supervision(sup: Supervisor) -> Iterator[Supervisor]:
    """Install ``sup`` as the ambient supervisor for the block (the
    telemetry-session model: one supervised call per process at a
    time; concurrent calls share the installed supervisor, which only
    blurs per-call sequence numbering, not correctness)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = sup
    try:
        yield sup
    finally:
        _ACTIVE = prev
