"""Dynamic (scenario-driven) runs with replication and repair.

Role-equivalent to the reference's ``pydcop run`` path (SURVEY §3.5:
``commands/run.py`` → orchestrator playing ``Scenario`` events against
``ResilientAgent``s).  The TPU engine's state is a pytree of arrays, so
dynamics become:

- **delay event** — solve for a deterministic round budget
  (``delay × rounds_per_second``; the batched engine is synchronous, so
  wall-clock delays map to round budgets for reproducibility).
- **remove_agent** — the agent's computations are orphaned; agents
  holding their replicas decide new hosts by solving a *reparation
  DCOP* on this same engine (``replication.repair``); computations with
  no live replica are **lost**: their variable freezes at its last
  value (it becomes an external variable) and the remaining problem is
  recompiled and resumed from the carried state.
- **add_agent** — joins the live pool (hosts future replicas/repairs).
- **set_value** — an external variable changes; constraints are
  re-sliced at the new value (recompile) and solving resumes.

Between events the solve state carries over at FULL fidelity whenever
the recompiled problem is unchanged (fingerprint match — every delay
and every clean migration): the complete algorithm state (Max-Sum
messages, DBA/GDBA weights, values) transfers into the next segment,
the batched equivalent of the reference resuming computations from
their replicated state.  When an event reshapes the problem (a lost
variable freezes into an external, an external value changes), the
carry degrades to declared initial values — exactly as the reference
loses the state of computations with no surviving replica.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Union

import numpy as np

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef
from pydcop_tpu.dcop.scenario import Scenario


def run_dynamic(
    dcop: DCOP,
    algo: str,
    algo_params: Optional[Dict[str, Any]] = None,
    scenario: Optional[Scenario] = None,
    distribution: Union[str, "Distribution"] = "oneagent",
    k_target: int = 0,
    rounds_per_second: float = 20.0,
    final_rounds: int = 100,
    seed: int = 0,
    timeout: Optional[float] = None,
    repair_algo: str = "mgm",
    mesh=None,
    n_shards: int = 1,
    chunk_size: int = 64,
    chunk_callback=None,
    pad_policy="none",
) -> Dict[str, Any]:
    """Play a scenario against a DCOP and return the result dict
    (reference ``pydcop run`` JSON shape + ``events`` log).

    With ``mesh``/``n_shards`` set, every solve segment runs sharded
    over the mesh (each segment's problem is recompiled with the same
    shard count after events change it).  ``chunk_callback`` is
    forwarded to each segment's :func:`run_batched` — the cross-process
    orchestrator uses it as its lockstep barrier, which works across
    segments because the segment schedule (budgets, seeds, event
    ordering) is a deterministic function of (dcop, scenario, seed)
    and therefore identical in every SPMD process.

    Segment compiles go through
    :class:`~pydcop_tpu.engine.incremental.IncrementalCompiler`: delay
    events reuse the cached compiled problem outright, ``set_value``
    events delta-update the affected device tables in place, and only
    structure-changing events (a variable freezing) pay a full host
    recompile.  ``pad_policy`` (``"pow2"``/``"pow2:<floor>"``,
    ``ops/padding.py``) additionally buckets array shapes so even
    structure changes reuse the previously compiled XLA executables
    when the new size lands in the same bucket — see
    ``docs/performance.md``.
    """
    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.distribution import load_distribution_module
    from pydcop_tpu.distribution.objects import Distribution
    from pydcop_tpu.graphs import load_graph_module
    from pydcop_tpu.replication import (
        repair_placement,
        replica_distribution,
    )

    t0 = time.perf_counter()
    module = load_algorithm_module(algo)
    if not hasattr(module, "step"):
        raise ValueError(
            f"Dynamic runs need a batched algorithm; {algo!r} is "
            "host-side (exact) — use a local-search or max-sum algorithm"
        )
    param_names = {p.name for p in module.algo_params}
    if "initial" not in param_names:
        raise ValueError(
            f"Algorithm {algo!r} does not support value carry-over "
            "(no 'initial' parameter); pick one that does"
        )
    params = prepare_algo_params(algo_params, module.algo_params)

    graph_module = load_graph_module(module.GRAPH_TYPE)
    graph = graph_module.build_computation_graph(dcop)
    computation_memory = getattr(module, "computation_memory", None)
    nodes = {n.name: n for n in graph.nodes}

    def footprint(comp: str) -> float:
        if computation_memory is None or comp not in nodes:
            return 1.0
        return float(computation_memory(nodes[comp]))

    live_agents: Dict[str, AgentDef] = dict(dcop.agents)
    if isinstance(distribution, Distribution):
        dist = distribution
    else:
        from pydcop_tpu.distribution import compute_distribution

        dist = compute_distribution(
            distribution,
            graph,
            live_agents.values(),
            hints=dcop.dist_hints,
            algo_module=module,
            computation_memory=computation_memory,
        )

    replicas = (
        replica_distribution(
            dist, live_agents.values(), k_target, footprint=footprint
        )
        if k_target > 0
        else None
    )

    # mutable run state
    frozen: Dict[str, Any] = {}  # lost variable → frozen value
    ext_overrides: Dict[str, Any] = {}
    current_values: Dict[str, Any] = {}
    events_log: List[Dict[str, Any]] = []
    traces: List[np.ndarray] = []
    cycles = 0
    messages = 0
    status = "finished"
    # full-state carry (reference parity: computations resume from
    # their REPLICATED STATE after a migration, not from scratch).
    # The batched state (Max-Sum messages, DBA weights, ...) transfers
    # verbatim across segments whenever the recompiled problem is
    # byte-identical (fingerprint match) — which is every delay event
    # and every remove_agent whose orphans all migrated.  Events that
    # freeze a variable or change an external value reshape the
    # problem, so only values carry there (the reference equivalently
    # loses the state of computations with no surviving replica).
    carry_state: Optional[Dict[str, np.ndarray]] = None
    carry_fp: Optional[str] = None
    state_transfers = 0

    # segment compiler: caches the compiled problem across segments,
    # delta-updates it on set_value events, full-recompiles only on
    # structure changes (see engine/incremental.py)
    from pydcop_tpu.engine.incremental import IncrementalCompiler

    compiler = IncrementalCompiler(
        dcop, n_shards=n_shards, pad_policy=pad_policy
    )

    def run_segment(n_rounds: int, seg_seed: int) -> bool:
        """One solve segment; returns whether full state carried."""
        nonlocal cycles, messages, current_values, status
        nonlocal carry_state, carry_fp, state_transfers
        import dataclasses as dc

        from pydcop_tpu.engine.batched import run_batched
        from pydcop_tpu.ops.compile import encode_assignment

        from pydcop_tpu.telemetry import get_tracer

        t_seg = time.perf_counter()
        problem, fp = compiler.compile(frozen, ext_overrides)
        if problem is None:
            return False  # everything frozen/lost
        carried = carry_state is not None and fp == carry_fp
        seg_params = dict(params)
        if not carried and current_values:
            real_names = tuple(
                problem.var_names[: problem.n_real_vars]
            )
            known = {
                name: current_values[name]
                for name in real_names
                if name in current_values
            }
            if len(known) == len(real_names):
                problem = dc.replace(
                    problem, init_idx=encode_assignment(problem, known)
                )
                seg_params["initial"] = "declared"
        remaining = (
            None if timeout is None else timeout - (time.perf_counter() - t0)
        )
        result = run_batched(
            problem,
            module,
            seg_params,
            rounds=n_rounds,
            seed=seg_seed,
            timeout=remaining,
            chunk_size=chunk_size,
            mesh=mesh,
            chunk_callback=chunk_callback,
            initial_state=carry_state if carried else None,
            return_state=True,
        )
        cycles += result.cycles
        messages += result.messages
        traces.append(np.asarray(result.cost_trace))
        current_values.update(result.assignment)
        carry_state = result.state
        carry_fp = fp
        if carried:
            state_transfers += 1
        if result.status == "timeout":
            status = "timeout"
        elif result.status == "degraded" and status != "timeout":
            # a NaN-quarantined segment (docs/faults.md recovery
            # matrix) reported its last-finite anytime best; later
            # segments restart from that trusted snapshot (the
            # poisoned carry was dropped above), but the run must
            # still SAY it degraded — sticky, like timeout
            status = "degraded"
        get_tracer().add_span(
            "segment", "cycle", t_seg, time.perf_counter() - t_seg,
            rounds=result.cycles, state_carried=carried,
        )
        return carried

    def remove_agent(name: str) -> Dict[str, Any]:
        nonlocal replicas, dist
        if name not in live_agents:
            return {"action": "remove_agent", "agent": name, "error": "unknown"}
        live_agents.pop(name)
        orphans = (
            dist.computations_hosted(name) if name in dist.agents else []
        )
        for comp in orphans:
            dist.remove_computation(comp)
        candidates = {
            comp: [
                a
                for a in (replicas.replicas(comp) if replicas else [])
                if a in live_agents
            ]
            for comp in orphans
        }
        remaining_cap = {
            a: live_agents[a].capacity
            - sum(footprint(c) for c in dist.computations_hosted(a))
            for a in live_agents
        }
        from pydcop_tpu.telemetry import get_tracer

        with get_tracer().span("repair", cat="repair", agent=name):
            placed = repair_placement(
                candidates,
                live_agents.values(),
                remaining_capacity=remaining_cap,
                footprint=footprint,
                algo=repair_algo,
                seed=seed,
            )
        lost = []
        for comp in orphans:
            if comp in placed:
                dist.host_on_agent(placed[comp], [comp])
            else:
                lost.append(comp)
                if comp in dcop.variables:
                    frozen[comp] = current_values.get(
                        comp, dcop.variables[comp].domain[0]
                    )
        # re-establish k-resilience over the survivors
        if replicas is not None and live_agents:
            replicas = replica_distribution(
                dist, live_agents.values(), k_target, footprint=footprint
            )
        return {
            "action": "remove_agent",
            "agent": name,
            "orphaned": sorted(orphans),
            "migrated": placed,
            "lost": sorted(lost),
        }

    # initial settle: run one segment before the first event, as the
    # reference deploys + runs before playing the scenario
    rng_seq = seed
    run_segment(final_rounds, rng_seq)

    for event in scenario or Scenario():
        if timeout is not None and time.perf_counter() - t0 > timeout:
            status = "timeout"
            break
        if event.is_delay:
            n = max(1, int(round(event.delay * rounds_per_second)))
            rng_seq += 1
            carried = run_segment(n, rng_seq)
            events_log.append(
                {"type": "delay", "rounds": n, "state_carried": carried}
            )
            continue
        for action in event.actions or []:
            args = action.args
            if action.type == "remove_agent":
                entry = remove_agent(args["agent"])
            elif action.type == "add_agent":
                name = args["agent"]
                live_agents[name] = AgentDef(
                    name, capacity=float(args.get("capacity", 100.0))
                )
                if replicas is not None:
                    replicas = replica_distribution(
                        dist,
                        live_agents.values(),
                        k_target,
                        footprint=footprint,
                    )
                entry = {"action": "add_agent", "agent": name}
            elif action.type == "set_value":
                vname = args["variable"]
                if vname not in dcop.external_variables:
                    entry = {
                        "action": "set_value",
                        "variable": vname,
                        "error": "not an external variable",
                    }
                else:
                    ev = dcop.external_variables[vname]
                    value = ev.domain.to_domain_value(args["value"])
                    ext_overrides[vname] = value
                    entry = {
                        "action": "set_value",
                        "variable": vname,
                        "value": value,
                    }
            else:
                entry = {"action": action.type, "error": "unknown action"}
            events_log.append({"type": "event", "id": event.id, **entry})

    # final settle after the last event
    rng_seq += 1
    run_segment(final_rounds, rng_seq)

    assignment = {
        name: current_values.get(name, frozen.get(name))
        for name in dcop.variables
    }
    ext_vals = {
        name: ext_overrides.get(name, ev.value)
        for name, ev in dcop.external_variables.items()
    }
    cost = dcop.solution_cost({**assignment, **ext_vals})
    trace = (
        np.concatenate(traces) if traces else np.zeros(0, dtype=np.float32)
    )
    return {
        "assignment": assignment,
        "cost": cost,
        "cycle": cycles,
        "msg_count": messages,
        "msg_size": messages,
        "status": status,
        "time": time.perf_counter() - t0,
        "events": events_log,
        "state_transfers": state_transfers,
        "lost_computations": sorted(frozen),
        "agents_final": sorted(live_agents),
        "replicas": replicas.mapping if replicas is not None else None,
        "cost_trace": trace.tolist(),
    }
