from pydcop_tpu.engine.batched import (
    RunResult,
    run_batched,
    run_many_batched,
)
