"""``pydcop_tpu.engine`` — the execution engines.

Re-exports are LAZY (PEP 562, same pattern as ``pydcop_tpu.ops``):
:mod:`pydcop_tpu.engine.batched` imports jax at module level, and an
eager re-export here would force that chain onto every consumer of
the package — including the deliberately jax-free
:mod:`pydcop_tpu.engine.host_batch` that ``api.solve_many`` uses for
pure host-path runs (DPOP ``util_device="never"``, SyncBB) and
:mod:`pydcop_tpu.engine.supervisor`, the (also jax-free) supervised
device-dispatch layer.
"""

_BATCHED_EXPORTS = {
    "RunResult",
    "run_batched",
    "run_many_batched",
}

_SUPERVISOR_EXPORTS = {
    "DeviceOOMError",
    "Supervisor",
    "SupervisorConfig",
    "UnrecoverableDeviceError",
    "get_supervisor",
    "make_supervisor",
    "supervision",
}

_SERVICE_EXPORTS = {
    "ServiceClient",
    "ServiceError",
    "ServiceServer",
    "SolverService",
    "TickPolicy",
}

__all__ = sorted(
    _BATCHED_EXPORTS | _SUPERVISOR_EXPORTS | _SERVICE_EXPORTS
)


def __getattr__(name):
    if name in _BATCHED_EXPORTS:
        import pydcop_tpu.engine.batched as _batched

        return getattr(_batched, name)
    if name in _SUPERVISOR_EXPORTS:
        import pydcop_tpu.engine.supervisor as _supervisor

        return getattr(_supervisor, name)
    if name in _SERVICE_EXPORTS:
        import pydcop_tpu.engine.service as _service

        return getattr(_service, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


def __dir__():
    return sorted(__all__)
