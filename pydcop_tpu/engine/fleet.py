"""The self-healing replicated serving fleet (docs/serving.md).

A single :class:`~pydcop_tpu.engine.service.SolverService` process is
a single point of failure.  This module adds the fleet layer on top
of the existing serving stack without changing the wire protocol:

- :class:`HashRing` — a pure consistent-hash placement over replica
  names.  Sessions and stateless requests pin to a replica by hash of
  the session id / dcop text; the STANDBY chain of a replica is its
  successor sequence in deterministic sorted-name order, so the
  replica a failed-over session lands on is exactly the replica its
  deltas were replicated to.  Every placement decision is a pure
  function of (replica set, key, dead set) — no wall clock, no RNG —
  which is what lets a seeded ``replica_kill`` soak replay
  bit-for-bit.
- :class:`FleetRouter` — a thin TCP router speaking the service's
  newline-JSON frames on both sides.  It forwards each frame to its
  ring owner through the PR 9 :class:`ServiceClient` retry machinery,
  PRESERVING the client's idempotency key and trace context
  (:meth:`ServiceClient.forward`), so a failover retry is answered
  from a reply cache — the router's own, or the standby's replicated
  one — instead of being re-solved (exactly-once).  Dead replicas are
  detected twice over: a forward transport failure marks the owner
  dead immediately (and re-forwards the SAME frame to the standby),
  and a ``/healthz`` watcher marks replicas dead/alive in the
  background (a ``draining`` replica counts as dead — planned
  rebalance is just drain + resume).
- :func:`standby_map` — the fleet controller's replication wiring:
  each replica streams its bounded session delta log to its ring
  successors (``k`` of them for k-resilience) via the ``standby`` /
  ``replicate`` wire ops (``engine/service.py``), so a SIGKILL'd
  replica's sessions resume on the standby through the existing
  :class:`~pydcop_tpu.engine.incremental.IncrementalCompiler` replay
  path — ``compile.incremental``-only after segment 1, bit-identical
  to an undisturbed service.

Session stickiness: once a session is served by a replica it stays
there while that replica is alive (a revived replica gets NEW ring
arcs back, never a session that moved — the moved session's state
lives on its current owner, which keeps replicating it down ITS
standby chain).  On the owner's death the session moves to the next
ALIVE successor — the first replica in its replication chain.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import socket
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import (
    Any,
    Dict,
    FrozenSet,
    Iterable,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
    Union,
)

from pydcop_tpu.engine.service import (
    ServiceClient,
    ServiceError,
    ServiceTransportError,
    _read_frame,
)
from pydcop_tpu.telemetry import get_metrics, get_tracer


class FleetError(ServiceError):
    """A fleet-level routing failure (typically: no live replica left
    to own the request's ring arc)."""


@dataclass(frozen=True)
class Replica:
    """One fleet member: its wire address and (optionally) the
    ``serve --metrics_port`` exporter address the health watcher
    polls and ``pydcop_tpu top`` aggregates."""

    name: str
    addr: str
    metrics: Optional[str] = None


#: virtual nodes per replica on the hash ring — enough that arcs stay
#: reasonably balanced for single-digit fleets without making lookup
#: tables large
_RING_VNODES = 64


def _ring_u(token: str) -> int:
    """Ring position from a keyed hash — the placement determinism
    core: the value depends on nothing but the token."""
    h = hashlib.blake2b(token.encode("utf-8"), digest_size=8).digest()
    return int.from_bytes(h, "big")


def ring_key(msg: Mapping[str, Any]) -> Tuple[str, Optional[str]]:
    """The routing key of one wire frame: ``(hash key, session)``.
    Session frames key on the session NAME (every segment of a
    session must land on the same replica); stateless frames key on
    the dcop payload text, so resubmissions of the same problem share
    a replica's warm compiled-problem cache.  Pure."""
    session = msg.get("session")
    if session:
        return f"s:{session}", str(session)
    dcop = msg.get("dcop")
    digest = hashlib.sha256(
        str(dcop).encode("utf-8", "replace")
    ).hexdigest()
    return f"d:{digest}", None


class HashRing:
    """Consistent-hash placement over a FIXED replica-name set.

    ``lookup`` walks the vnode ring; ``successors`` / ``next_alive``
    walk the deterministic sorted-name cycle — the standby chain.
    Both are pure functions of their arguments, so two routers (or
    two seeded runs) with the same replica set make identical
    placement and failover decisions."""

    def __init__(
        self, names: Iterable[str], vnodes: int = _RING_VNODES
    ) -> None:
        self.names: Tuple[str, ...] = tuple(sorted(set(names)))
        if not self.names:
            raise ValueError("HashRing needs at least one replica")
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        points: List[Tuple[int, str]] = []
        for name in self.names:
            for v in range(vnodes):
                points.append((_ring_u(f"{name}#{v}"), name))
        points.sort()
        self._points = points
        self._hashes = [p[0] for p in points]

    def lookup(self, key: str) -> str:
        """The ring owner of ``key``: the first vnode at or after the
        key's hash position, wrapping."""
        i = bisect.bisect_left(self._hashes, _ring_u(key))
        if i == len(self._points):
            i = 0
        return self._points[i][1]

    def successor(self, name: str) -> str:
        """The next DISTINCT replica after ``name`` in sorted cyclic
        order — its first standby, and the replica its sessions fail
        over to."""
        if name not in self.names:
            raise ValueError(f"unknown replica {name!r}")
        i = self.names.index(name)
        return self.names[(i + 1) % len(self.names)]

    def successors(self, name: str, k: int = 1) -> List[str]:
        """The first ``k`` distinct successors of ``name`` (its
        standby chain, nearest first).  Capped at the other replicas
        that exist."""
        out: List[str] = []
        cur = name
        for _ in range(min(k, len(self.names) - 1)):
            cur = self.successor(cur)
            out.append(cur)
        return out

    def next_alive(
        self, name: str, dead: FrozenSet[str]
    ) -> str:
        """``name`` itself if alive, else the first alive replica in
        its successor chain — the failover rule that keeps routing
        aligned with the replication chain."""
        cur = name
        for _ in range(len(self.names)):
            if cur not in dead:
                return cur
            cur = self.successor(cur)
        raise FleetError(
            "fleet: no live replica left "
            f"({len(self.names)} registered, all marked dead)"
        )


def standby_map(
    names: Iterable[str], k: int = 1
) -> Dict[str, List[str]]:
    """Replica name → its ``k`` standby names (ring successor chain,
    nearest first) — what the fleet controller turns into per-replica
    ``standby`` wire ops.  Pure."""
    ring = HashRing(names)
    return {name: ring.successors(name, k) for name in ring.names}


def _as_replicas(
    replicas: Union[
        Mapping[str, str], Sequence[Replica], Sequence[Tuple]
    ]
) -> "OrderedDict[str, Replica]":
    out: "OrderedDict[str, Replica]" = OrderedDict()
    if isinstance(replicas, Mapping):
        for name in sorted(replicas):
            out[str(name)] = Replica(str(name), str(replicas[name]))
        return out
    reps = []
    for r in replicas:
        if isinstance(r, Replica):
            reps.append(r)
        else:
            name, addr = r[0], r[1]
            metrics = r[2] if len(r) > 2 else None
            reps.append(Replica(str(name), str(addr), metrics))
    for r in sorted(reps, key=lambda r: r.name):
        out[r.name] = r
    return out


#: ops the router ROUTES to a single ring owner (everything session-
#: or problem-addressed); the rest are fleet-local or broadcast
_ROUTED_OPS = ("solve", "infer", "close_session")

#: how long one downstream forward may retry before the router
#: declares the owner dead and fails the frame over to its standby —
#: the knob that bounds takeover latency to roughly one tick budget
#: plus this window
_FORWARD_RETRY_WINDOW_S = 0.75

#: downstream client socket timeout — bounds both the connect to a
#: replica and the wait for one reply.  Generous on purpose: a slow
#: first-compile solve must not read as a dead replica (a SIGKILL'd
#: process fails the socket immediately regardless, so takeover
#: latency does not ride on this); a genuinely hung replica is the
#: ``/healthz`` watcher's job
_DOWNSTREAM_TIMEOUT_S = 60.0


class FleetRouter:
    """Consistent-hash front for N :class:`ServiceServer` replicas.

    Speaks the service's newline-JSON wire protocol upstream (so
    :class:`ServiceClient` works against it unchanged) and forwards
    frames downstream through :meth:`ServiceClient.forward`, which
    preserves the client's idempotency key and trace context.  One
    handler thread per upstream connection; each handler keeps its
    own downstream clients, so one slow client never blocks another.

    Exactly-once across failover: the router caches ok solve replies
    in a bounded LRU by the CLIENT's ikey (a retry of an
    already-answered request replays here without touching a
    replica); a retry racing an in-flight solve attaches at the
    owning replica's in-flight table; and a failover re-forward of
    the SAME frame to the standby is answered from the standby's
    replicated reply cache when the original reply was computed, or
    legitimately solved exactly once when it never was.
    """

    def __init__(
        self,
        replicas: Union[
            Mapping[str, str], Sequence[Replica], Sequence[Tuple]
        ],
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        health_interval: float = 0.25,
        retry_window: float = _FORWARD_RETRY_WINDOW_S,
        connect_timeout: float = _DOWNSTREAM_TIMEOUT_S,
        reply_cache: int = 1024,
        backoff_seed: int = 0,
        autostart: bool = True,
    ) -> None:
        self.replicas = _as_replicas(replicas)
        if not self.replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.ring = HashRing(self.replicas)
        self.health_interval = health_interval
        self.retry_window = retry_window
        self.connect_timeout = connect_timeout
        self._backoff_seed = backoff_seed

        self._lock = threading.Lock()
        self._dead: set = set()
        self._owner: Dict[str, str] = {}  # session -> replica name
        self._replies: "OrderedDict[str, Dict[str, Any]]" = (
            OrderedDict()
        )
        self._reply_cache_max = reply_cache

        self._stats_lock = threading.Lock()
        self._n_requests = 0
        self._n_forwards = 0
        self._n_failovers = 0
        self._n_replayed = 0
        self._n_marked_dead = 0
        self._n_revived = 0

        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._server = socket.create_server((host, port))
        self.address: Tuple[str, int] = (
            host, self._server.getsockname()[1]
        )
        self._accept: Optional[threading.Thread] = None
        self._health_thread: Optional[threading.Thread] = None
        if autostart:
            self.start()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._accept is not None:
            return
        self._accept = threading.Thread(
            target=self._accept_loop, name="fleet-router-accept",
            daemon=True,
        )
        self._accept.start()
        if any(r.metrics for r in self.replicas.values()):
            self._health_thread = threading.Thread(
                target=self._health_loop, name="fleet-router-health",
                daemon=True,
            )
            self._health_thread.start()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._shutdown.wait(timeout)

    def request_shutdown(self) -> None:
        self._shutdown.set()

    def close(self) -> None:
        self._shutdown.set()
        try:
            self._server.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in list(self._threads):
            t.join(timeout=5)
        if self._health_thread is not None:
            self._health_thread.join(timeout=5)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- liveness --------------------------------------------------------

    def mark_dead(self, name: str) -> None:
        """Mark a replica dead: its ring arcs and sticky sessions
        re-pin to the next alive successor on the very next frame."""
        with self._lock:
            if name in self._dead or name not in self.replicas:
                return
            self._dead.add(name)
        with self._stats_lock:
            self._n_marked_dead += 1
        met = get_metrics()
        if met.enabled:
            met.inc("fleet.marked_dead")
        tr = get_tracer()
        if tr.enabled:
            tr.event("fleet-dead", cat="fleet", replica=name)

    def mark_alive(self, name: str) -> None:
        """Mark a replica alive again (a resumed drain, a restarted
        process): it gets NEW placements back; sessions that moved
        stay with their current owner."""
        with self._lock:
            if name not in self._dead:
                return
            self._dead.discard(name)
        with self._stats_lock:
            self._n_revived += 1
        met = get_metrics()
        if met.enabled:
            met.inc("fleet.revived")
        tr = get_tracer()
        if tr.enabled:
            tr.event("fleet-revived", cat="fleet", replica=name)

    def dead(self) -> List[str]:
        with self._lock:
            return sorted(self._dead)

    def _health_loop(self) -> None:
        from pydcop_tpu.telemetry.export import http_get

        while not self._shutdown.wait(self.health_interval):
            for name in self.ring.names:
                rep = self.replicas[name]
                if not rep.metrics:
                    continue
                try:
                    doc = json.loads(
                        http_get(
                            f"http://{rep.metrics}/healthz",
                            timeout=max(self.health_interval, 1.0),
                        )
                    )
                    ok = doc.get("status") == "ok"
                except (OSError, ValueError):
                    ok = False
                if ok:
                    self.mark_alive(name)
                else:
                    self.mark_dead(name)

    # -- placement (pure decisions) --------------------------------------

    def _pick_owner(
        self,
        key: str,
        prev: Optional[str],
        dead: FrozenSet[str],
    ) -> str:
        """The replica that owns this frame: the session's current
        owner while it lives, else the ring owner — in both cases
        walked down the successor chain past dead replicas, which is
        exactly the replication chain.  Pure in its arguments."""
        start = prev if prev is not None else self.ring.lookup(key)
        return self.ring.next_alive(start, dead)

    # -- health / stats --------------------------------------------------

    def health(self) -> Dict[str, Any]:
        """The router's aggregate ``/healthz`` document: fleet status
        plus a per-replica roster (``pydcop_tpu top`` expands the
        roster's ``metrics`` addresses into per-replica rows)."""
        with self._lock:
            dead = set(self._dead)
            sessions = len(self._owner)
        roster = {
            name: {
                "addr": rep.addr,
                "metrics": rep.metrics,
                "alive": name not in dead,
            }
            for name, rep in self.replicas.items()
        }
        status = (
            "down"
            if len(dead) == len(self.replicas)
            else "degraded" if dead else "ok"
        )
        with self._stats_lock:
            return {
                "status": status,
                "fleet": True,
                "replicas": roster,
                "sessions": sessions,
                "requests": self._n_requests,
                "failovers": self._n_failovers,
            }

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            dead = sorted(self._dead)
            sessions = len(self._owner)
        with self._stats_lock:
            return {
                "replicas": len(self.replicas),
                "dead": dead,
                "sessions": sessions,
                "requests": self._n_requests,
                "forwards": self._n_forwards,
                "failovers": self._n_failovers,
                "replayed_replies": self._n_replayed,
                "marked_dead": self._n_marked_dead,
                "revived": self._n_revived,
            }

    # -- the frame loop --------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _addr = self._server.accept()
            except OSError:
                return  # closed
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(
                target=self._handle, args=(conn,),
                name="fleet-router-conn", daemon=True,
            )
            self._threads.append(t)
            t.start()

    @staticmethod
    def _send(conn: socket.socket, obj: Dict[str, Any]) -> bool:
        try:
            conn.sendall((json.dumps(obj) + "\n").encode("utf-8"))
            return True
        except (OSError, ValueError):
            return False

    def _handle(self, conn: socket.socket) -> None:
        reader = conn.makefile("rb")
        clients: Dict[str, ServiceClient] = {}
        try:
            while not self._shutdown.is_set():
                msg, err = _read_frame(reader)
                if msg is None and err is None:
                    return  # peer closed
                if err is not None:
                    if not self._send(
                        conn,
                        {
                            "id": None,
                            "ok": False,
                            "error": err,
                            "frame_rejected": True,
                        },
                    ):
                        return
                    continue
                try:
                    reply = self._serve(msg, clients)
                except Exception as e:  # noqa: BLE001 — the error
                    # IS the reply; one bad frame must not drop the
                    # connection and every request behind it
                    reply = {
                        "ok": False,
                        "error": f"{type(e).__name__}: {e}",
                    }
                reply["id"] = msg.get("id")
                if not self._send(conn, reply):
                    return
                if msg.get("op") == "shutdown":
                    self._shutdown.set()
                    return
        finally:
            for cli in clients.values():
                cli.close()
            try:
                reader.close()
                conn.close()
            except OSError:
                pass
            with self._lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass
            try:
                self._threads.remove(threading.current_thread())
            except ValueError:
                pass

    def _client(
        self, clients: Dict[str, ServiceClient], name: str
    ) -> ServiceClient:
        cli = clients.get(name)
        if cli is None:
            cli = ServiceClient(
                self.replicas[name].addr,
                timeout=self.connect_timeout,
                retry_window=self.retry_window,
                backoff_seed=self._backoff_seed,
            )
            clients[name] = cli
        return cli

    def _drop_client(
        self, clients: Dict[str, ServiceClient], name: str
    ) -> None:
        cli = clients.pop(name, None)
        if cli is not None:
            cli.close()

    def _cache_reply(
        self, ikey: str, reply: Dict[str, Any]
    ) -> None:
        with self._lock:
            self._replies[ikey] = dict(reply)
            self._replies.move_to_end(ikey)
            while len(self._replies) > self._reply_cache_max:
                self._replies.popitem(last=False)

    def _note_replay(self) -> None:
        with self._stats_lock:
            self._n_replayed += 1
        met = get_metrics()
        if met.enabled:
            met.inc("fleet.replayed_replies")

    def _serve(
        self, msg: Dict[str, Any], clients: Dict[str, ServiceClient]
    ) -> Dict[str, Any]:
        op = msg.get("op")
        if op == "ping":
            return {"ok": True, "pong": True, "fleet": True}
        if op == "stats":
            return {"ok": True, "stats": self._fleet_stats(clients)}
        if op == "shutdown":
            self._broadcast_shutdown(clients)
            return {"ok": True, "stopping": True}
        if op in _ROUTED_OPS:
            return self._forward_routed(msg, clients)
        raise ServiceError(f"unknown op {op!r}")

    def _fleet_stats(
        self, clients: Dict[str, ServiceClient]
    ) -> Dict[str, Any]:
        per: Dict[str, Any] = {}
        with self._lock:
            dead = set(self._dead)
        for name in self.ring.names:
            if name in dead:
                per[name] = {"error": "dead"}
                continue
            try:
                per[name] = self._client(clients, name).stats()
            except (ServiceError, OSError) as e:
                per[name] = {
                    "error": f"{type(e).__name__}: {e}"[:200]
                }
        return {"fleet": self.stats(), "replicas": per}

    def _broadcast_shutdown(
        self, clients: Dict[str, ServiceClient]
    ) -> None:
        with self._lock:
            dead = set(self._dead)
        for name in self.ring.names:
            if name in dead:
                continue
            try:
                self._client(clients, name).shutdown()
            except (ServiceError, OSError):
                pass

    def _forward_routed(
        self, msg: Dict[str, Any], clients: Dict[str, ServiceClient]
    ) -> Dict[str, Any]:
        met = get_metrics()
        with self._stats_lock:
            self._n_requests += 1
        if met.enabled:
            met.inc("fleet.requests")
        ikey = msg.get("ikey")
        if ikey is not None:
            with self._lock:
                cached = self._replies.get(ikey)
                if cached is not None:
                    self._replies.move_to_end(ikey)
            if cached is not None:
                # a retry of an already-answered request: replay at
                # the router, never touch a replica
                self._note_replay()
                return dict(cached)
        key, session = ring_key(msg)
        for _ in range(len(self.replicas) + 1):
            with self._lock:
                dead = frozenset(self._dead)
                prev = (
                    self._owner.get(session) if session else None
                )
            owner = self._pick_owner(key, prev, dead)
            if session:
                with self._lock:
                    self._owner[session] = owner
            try:
                cli = self._client(clients, owner)
                with self._stats_lock:
                    self._n_forwards += 1
                reply = cli.forward(msg)
            except (ServiceTransportError, OSError) as e:
                # the owner is gone: mark it dead and re-forward the
                # SAME frame (same ikey, same trace) to its standby —
                # the replicated reply cache replays a computed
                # answer; an uncomputed one is solved exactly once
                self.mark_dead(owner)
                self._drop_client(clients, owner)
                with self._stats_lock:
                    self._n_failovers += 1
                if met.enabled:
                    met.inc("fleet.failovers")
                tr = get_tracer()
                if tr.enabled:
                    tr.event(
                        "fleet-failover", cat="fleet",
                        replica=owner, session=session,
                        error=f"{type(e).__name__}"[:80],
                    )
                continue
            if (
                session
                and msg.get("op") == "close_session"
                and reply.get("ok")
            ):
                with self._lock:
                    self._owner.pop(session, None)
            if ikey is not None and reply.get("ok"):
                self._cache_reply(ikey, reply)
            return reply
        raise FleetError(
            "fleet: no live replica answered the request "
            f"(replicas={len(self.replicas)}, dead={self.dead()})"
        )
