"""Shared 0/1 ILP placement model, solved with ``scipy.optimize.milp``.

The reference's ILP distributions (``ilp_fgdp.py``, ``ilp_compref.py``)
shell out to CBC/GLPK through ``pulp``; here the same mixed-integer
program is handed to scipy's HiGHS backend — placement is an offline
host-side step, so no TPU work is involved (SURVEY §2.8).

Model
-----
Binary ``x[c, a]`` = computation *c* hosted on agent *a*.

    min   Σ_{c,a} hosting_w · hcost(a, c) · x[c,a]
        + Σ_{(c1,c2) ∈ links, a ≠ b} comm_w · load(c1,c2) · route(a,b)
              · z[c1,c2,a,b]
    s.t.  Σ_a x[c,a] = 1                        ∀ c
          Σ_c mem(c) · x[c,a] ≤ capacity(a)     ∀ a
          z[c1,c2,a,b] ≥ x[c1,a] + x[c2,b] − 1  (linearized product)
          x binary, z ∈ [0, 1]

Because every z coefficient in the objective is ≥ 0 and minimized, z
settles at ``max(0, x1 + x2 − 1)`` — exactly the product — without
being declared integer, keeping the MIP small.
"""

from __future__ import annotations

from itertools import combinations
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from pydcop_tpu.distribution.objects import (
    Distribution,
    DistributionHints,
    ImpossibleDistributionException,
)


def solve_ilp_placement(
    computation_graph,
    agentsdef: Iterable,
    hints: Optional[DistributionHints],
    computation_memory: Optional[Callable],
    communication_load: Optional[Callable],
    comm_w: float = 1.0,
    hosting_w: float = 1.0,
    time_limit: float = 60.0,
) -> Distribution:
    from scipy.optimize import Bounds, LinearConstraint, milp
    from scipy.sparse import lil_matrix

    agents = list(agentsdef)
    nodes = {n.name: n for n in computation_graph.nodes}
    comps = sorted(nodes)
    anames = [a.name for a in agents]
    n_c, n_a = len(comps), len(agents)
    if n_a == 0:
        raise ImpossibleDistributionException("No agents")
    cidx = {c: i for i, c in enumerate(comps)}
    aidx = {a: i for i, a in enumerate(anames)}

    def xvar(c: int, a: int) -> int:
        return c * n_a + a

    n_x = n_c * n_a

    # pairwise communication terms; a pair connected by several links
    # accumulates each link's load, matching distribution_cost's
    # per-link summation (pydcop_tpu/distribution/_cost.py)
    pair_load: Dict[Tuple[int, int], float] = {}
    if communication_load is not None and comm_w != 0.0:
        for link in computation_graph.links:
            members = [m for m in link.nodes if m in nodes]
            for c1, c2 in combinations(sorted(members), 2):
                load = float(communication_load(nodes[c1], c2))
                if load:
                    key = (cidx[c1], cidx[c2])
                    pair_load[key] = pair_load.get(key, 0.0) + load
    pairs: List[Tuple[int, int, float]] = [
        (c1, c2, load) for (c1, c2), load in sorted(pair_load.items())
    ]

    # z variables: one per (pair, a, b) with a != b and route > 0
    z_entries: List[Tuple[int, int, int, int, float]] = []
    for p, (c1, c2, load) in enumerate(pairs):
        for ai in range(n_a):
            for bi in range(n_a):
                if ai == bi:
                    continue
                route = agents[ai].route(anames[bi])
                if route:
                    z_entries.append((c1, c2, ai, bi, load * route))
    n_z = len(z_entries)
    n_vars = n_x + n_z

    obj = np.zeros(n_vars)
    if hosting_w:
        for c in comps:
            for ai, agent in enumerate(agents):
                obj[xvar(cidx[c], ai)] += hosting_w * agent.hosting_cost(c)
    for zi, (c1, c2, ai, bi, w) in enumerate(z_entries):
        obj[n_x + zi] = comm_w * w

    constraints = []

    # assignment: sum_a x[c,a] = 1
    A = lil_matrix((n_c, n_vars))
    for c in range(n_c):
        for a in range(n_a):
            A[c, xvar(c, a)] = 1.0
    constraints.append(LinearConstraint(A.tocsr(), 1.0, 1.0))

    # capacity
    if computation_memory is not None:
        mem = np.array(
            [float(computation_memory(nodes[c])) for c in comps]
        )
        if mem.any():
            A = lil_matrix((n_a, n_vars))
            for a in range(n_a):
                for c in range(n_c):
                    A[a, xvar(c, a)] = mem[c]
            caps = np.array([a.capacity for a in agents])
            constraints.append(LinearConstraint(A.tocsr(), -np.inf, caps))

    # must_host pins: x[c, pinned_agent] = 1
    if hints is not None:
        for agent_name, pinned in hints.must_host_map.items():
            if agent_name not in aidx:
                raise ImpossibleDistributionException(
                    f"must_host references unknown agent {agent_name}"
                )
            for comp in pinned:
                if comp not in cidx:
                    continue
                A = lil_matrix((1, n_vars))
                A[0, xvar(cidx[comp], aidx[agent_name])] = 1.0
                constraints.append(LinearConstraint(A.tocsr(), 1.0, 1.0))
        # host_with: members share an agent → x[c1,a] - x[c2,a] = 0 ∀a
        done = set()
        for comp in comps:
            for mate in hints.host_with(comp):
                if mate not in cidx or (mate, comp) in done:
                    continue
                done.add((comp, mate))
                A = lil_matrix((n_a, n_vars))
                for a in range(n_a):
                    A[a, xvar(cidx[comp], a)] = 1.0
                    A[a, xvar(cidx[mate], a)] = -1.0
                constraints.append(LinearConstraint(A.tocsr(), 0.0, 0.0))

    # z linearization: x1 + x2 - z <= 1
    if n_z:
        A = lil_matrix((n_z, n_vars))
        for zi, (c1, c2, ai, bi, _w) in enumerate(z_entries):
            A[zi, xvar(c1, ai)] = 1.0
            A[zi, xvar(c2, bi)] = 1.0
            A[zi, n_x + zi] = -1.0
        constraints.append(LinearConstraint(A.tocsr(), -np.inf, 1.0))

    integrality = np.concatenate([np.ones(n_x), np.zeros(n_z)])
    bounds = Bounds(np.zeros(n_vars), np.ones(n_vars))
    # time_limit: identical agents make the branch-and-bound highly
    # symmetric; accept the incumbent rather than spin for optimality
    res = milp(
        c=obj,
        constraints=constraints,
        integrality=integrality,
        bounds=bounds,
        options={"time_limit": time_limit},
    )
    if res.x is None:
        raise ImpossibleDistributionException(
            f"ILP infeasible or failed: {res.message}"
        )

    mapping: Dict[str, List[str]] = {a: [] for a in anames}
    x = res.x[:n_x].reshape(n_c, n_a)
    for c, comp in enumerate(comps):
        mapping[anames[int(np.argmax(x[c]))]].append(comp)
    return Distribution(mapping)
