"""Distribution (computation → agent placement) data objects.

Role-equivalent to ``pydcop/distribution/objects.py``: ``Distribution``
(the mapping), ``DistributionHints`` (yaml ``distribution_hints``), and
the exception raised when no valid placement exists.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Mapping, Optional

from pydcop_tpu.utils.simple_repr import SimpleRepr


class ImpossibleDistributionException(Exception):
    pass


class DistributionHints(SimpleRepr):
    """Placement hints from the problem yaml: ``must_host`` (agent →
    computations it must host) and ``host_with`` (computation →
    computations that must share its agent)."""

    def __init__(
        self,
        must_host: Optional[Mapping[str, List[str]]] = None,
        host_with: Optional[Mapping[str, List[str]]] = None,
    ):
        self._must_host = {k: list(v) for k, v in (must_host or {}).items()}
        self._host_with = {k: list(v) for k, v in (host_with or {}).items()}

    def must_host(self, agent_name: str) -> List[str]:
        return list(self._must_host.get(agent_name, []))

    def host_with(self, computation_name: str) -> List[str]:
        """Transitive closure of the host_with relation for a computation."""
        group = {computation_name}
        frontier = [computation_name]
        while frontier:
            c = frontier.pop()
            for other, mates in self._host_with.items():
                linked = set(mates) | {other}
                if c in linked:
                    new = linked - group
                    group |= new
                    frontier.extend(new)
        group.discard(computation_name)
        return sorted(group)

    @property
    def must_host_map(self) -> Dict[str, List[str]]:
        return {k: list(v) for k, v in self._must_host.items()}

    @property
    def host_with_map(self) -> Dict[str, List[str]]:
        return {k: list(v) for k, v in self._host_with.items()}

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY, simple_repr

        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "must_host": simple_repr(self._must_host),
            "host_with": simple_repr(self._host_with),
        }

    @classmethod
    def _from_repr(cls, r: dict):
        from pydcop_tpu.utils.simple_repr import from_repr

        return cls(
            from_repr(r.get("must_host", {})) or {},
            from_repr(r.get("host_with", {})) or {},
        )


class Distribution(SimpleRepr):
    """A mapping computation name → agent name.

    >>> d = Distribution({'a1': ['v1', 'v2'], 'a2': ['v3']})
    >>> d.agent_for('v3')
    'a2'
    """

    def __init__(self, mapping: Mapping[str, Iterable[str]]):
        self._mapping: Dict[str, List[str]] = {
            a: list(comps) for a, comps in mapping.items()
        }
        self._agent_for: Dict[str, str] = {}
        for agent, comps in self._mapping.items():
            for c in comps:
                if c in self._agent_for:
                    raise ValueError(
                        f"Computation {c} assigned to both "
                        f"{self._agent_for[c]} and {agent}"
                    )
                self._agent_for[c] = agent

    @property
    def agents(self) -> List[str]:
        return list(self._mapping)

    @property
    def computations(self) -> List[str]:
        return list(self._agent_for)

    def agent_for(self, computation: str) -> str:
        try:
            return self._agent_for[computation]
        except KeyError:
            raise KeyError(f"No agent hosts computation {computation}")

    def computations_hosted(self, agent: str) -> List[str]:
        return list(self._mapping.get(agent, []))

    def has_computation(self, computation: str) -> bool:
        return computation in self._agent_for

    @property
    def mapping(self) -> Dict[str, List[str]]:
        return {a: list(cs) for a, cs in self._mapping.items()}

    def host_on_agent(self, agent: str, computations: List[str]) -> None:
        already = [c for c in computations if c in self._agent_for]
        if already:
            raise ValueError(f"Computation(s) {already} already hosted")
        for c in computations:
            self._agent_for[c] = agent
        self._mapping.setdefault(agent, []).extend(computations)

    def remove_computation(self, computation: str) -> None:
        agent = self._agent_for.pop(computation)
        self._mapping[agent].remove(computation)

    def is_hosted(
        self, computations: Iterable[str]
    ) -> bool:
        return all(c in self._agent_for for c in computations)

    def __eq__(self, other):
        return (
            isinstance(other, Distribution)
            and other._agent_for == self._agent_for
        )

    def __repr__(self) -> str:
        return f"Distribution({self._mapping})"

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY, simple_repr

        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "mapping": simple_repr(self._mapping),
        }

    @classmethod
    def _from_repr(cls, r: dict):
        from pydcop_tpu.utils.simple_repr import from_repr

        return cls(from_repr(r["mapping"]))
