"""Shared distribution-cost evaluation.

The cost of a placement, as the reference's hosting-cost distributions
define it: ``comm + RATIO_HOST_COMM * hosting`` where ``comm`` sums
``communication_load(link) * route(agent_i, agent_j)`` over graph links
whose endpoints land on different agents, and ``hosting`` sums each
agent's hosting cost for the computations it hosts.
"""

from __future__ import annotations

from itertools import combinations
from typing import Callable, Iterable, Optional, Tuple

# Same trade-off ratio the reference uses between hosting and
# communication objectives in its hosting-cost-aware distributions.
RATIO_HOST_COMM = 0.8


def distribution_cost(
    distribution,
    computation_graph,
    agentsdef: Iterable,
    computation_memory: Optional[Callable] = None,
    communication_load: Optional[Callable] = None,
) -> Tuple[float, float, float]:
    """Return ``(total, communication, hosting)`` for a placement."""
    agents = {a.name: a for a in agentsdef}
    nodes = {n.name: n for n in computation_graph.nodes}

    comm = 0.0
    if communication_load is not None:
        for link in computation_graph.links:
            members = [n for n in link.nodes if n in nodes]
            for c1, c2 in combinations(members, 2):
                if not (
                    distribution.has_computation(c1)
                    and distribution.has_computation(c2)
                ):
                    continue
                a1 = distribution.agent_for(c1)
                a2 = distribution.agent_for(c2)
                if a1 == a2:
                    continue
                load = float(communication_load(nodes[c1], c2))
                comm += load * agents[a1].route(a2)

    hosting = 0.0
    for comp in distribution.computations:
        agent = agents[distribution.agent_for(comp)]
        hosting += agent.hosting_cost(comp)

    total = comm + RATIO_HOST_COMM * hosting
    return total, comm, hosting
