"""Optimal ILP SECP placement (reference: the ``oilp_secp_*``
distribution modules — fgdp/cgdp variants are covered by the one
``distribute`` since the graph model arrives as an argument).

Same mixed-integer program as ``ilp_compref`` (hosting +
communication·route objective, capacity constraints, HiGHS backend —
see ``_ilp``), with the SECP actuator pinning added as ``must_host``
constraints, so only factor/rule computations are free variables.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from pydcop_tpu.distribution._cost import (  # noqa: F401  (re-export)
    RATIO_HOST_COMM,
    distribution_cost,
)
from pydcop_tpu.distribution._ilp import solve_ilp_placement
from pydcop_tpu.distribution._secp import secp_pins
from pydcop_tpu.distribution.objects import Distribution, DistributionHints


def distribute(
    computation_graph,
    agentsdef: Iterable,
    hints: Optional[DistributionHints] = None,
    computation_memory: Optional[Callable] = None,
    communication_load: Optional[Callable] = None,
) -> Distribution:
    agents = list(agentsdef)
    pins = secp_pins(computation_graph, agents, hints)
    must_host = {}
    for comp, agent in pins.items():
        must_host.setdefault(agent, []).append(comp)
    pinned_hints = DistributionHints(
        must_host=must_host,
        host_with=hints.host_with_map if hints is not None else None,
    )
    return solve_ilp_placement(
        computation_graph,
        agents,
        pinned_hints,
        computation_memory,
        communication_load,
        comm_w=1.0,
        hosting_w=RATIO_HOST_COMM,
    )
