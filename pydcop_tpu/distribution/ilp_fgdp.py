"""Optimal factor-graph distribution as an ILP (FGDP).

Role-equivalent to ``pydcop/distribution/ilp_fgdp.py``: exact placement
of a factor graph's computations minimizing inter-agent communication
(edge load × route cost) under agent capacities.  The reference solves
it with pulp→CBC; here scipy/HiGHS (see ``_ilp``).
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from pydcop_tpu.distribution._cost import distribution_cost as _dc
from pydcop_tpu.distribution._ilp import solve_ilp_placement
from pydcop_tpu.distribution.objects import Distribution, DistributionHints


def distribute(
    computation_graph,
    agentsdef: Iterable,
    hints: Optional[DistributionHints] = None,
    computation_memory: Optional[Callable] = None,
    communication_load: Optional[Callable] = None,
) -> Distribution:
    return solve_ilp_placement(
        computation_graph,
        agentsdef,
        hints,
        computation_memory,
        communication_load,
        comm_w=1.0,
        hosting_w=0.0,  # FGDP: pure communication objective
    )


def distribution_cost(
    distribution,
    computation_graph,
    agentsdef,
    computation_memory=None,
    communication_load=None,
):
    return _dc(
        distribution,
        computation_graph,
        agentsdef,
        computation_memory,
        communication_load,
    )
