"""Greedy communication/hosting-cost distribution heuristic.

Role-equivalent to ``pydcop/distribution/heur_comhost.py`` (the SECP
heuristic): computations are placed one at a time, highest-degree
first; each goes to the agent minimizing

    hosting_cost(agent, comp)
    + sum over already-placed neighbors n of
        communication_load(comp, n) * route(agent, agent_of(n))

subject to remaining capacity.  Deterministic (ties broken by agent
name) so placements are reproducible.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, Optional

from pydcop_tpu.distribution._cost import (
    RATIO_HOST_COMM,
    distribution_cost as _dc,
)
from pydcop_tpu.distribution.objects import (
    Distribution,
    DistributionHints,
    ImpossibleDistributionException,
)


def distribute(
    computation_graph,
    agentsdef: Iterable,
    hints: Optional[DistributionHints] = None,
    computation_memory: Optional[Callable] = None,
    communication_load: Optional[Callable] = None,
) -> Distribution:
    agents = {a.name: a for a in agentsdef}
    if not agents:
        raise ImpossibleDistributionException("No agents")
    nodes = {n.name: n for n in computation_graph.nodes}
    remaining: Dict[str, float] = {n: a.capacity for n, a in agents.items()}
    placed: Dict[str, str] = {}
    hints = hints or DistributionHints()

    for agent_name, comps in hints.must_host_map.items():
        for comp in comps:
            if comp in nodes and comp not in placed:
                placed[comp] = agent_name
                if computation_memory is not None:
                    remaining[agent_name] -= float(
                        computation_memory(nodes[comp])
                    )

    order = sorted(
        (c for c in nodes if c not in placed),
        key=lambda c: (-len(nodes[c].neighbors), c),
    )
    for comp in order:
        node = nodes[comp]
        foot = (
            float(computation_memory(node))
            if computation_memory is not None
            else 0.0
        )
        best_agent, best_cost = None, None
        for aname in sorted(agents):
            if remaining[aname] < foot:
                continue
            agent = agents[aname]
            cost = RATIO_HOST_COMM * agent.hosting_cost(comp)
            for nb in node.neighbors:
                if nb in placed:
                    load = (
                        float(communication_load(node, nb))
                        if communication_load is not None
                        else 1.0
                    )
                    cost += load * agent.route(placed[nb])
            if best_cost is None or cost < best_cost:
                best_agent, best_cost = aname, cost
        if best_agent is None:
            raise ImpossibleDistributionException(
                f"No agent has capacity {foot:.1f} left for {comp}"
            )
        placed[comp] = best_agent
        remaining[best_agent] -= foot

    mapping: Dict[str, list] = {a: [] for a in agents}
    for comp, agent in placed.items():
        mapping[agent].append(comp)
    return Distribution(mapping)


def distribution_cost(
    distribution,
    computation_graph,
    agentsdef,
    computation_memory=None,
    communication_load=None,
):
    return _dc(
        distribution,
        computation_graph,
        agentsdef,
        computation_memory,
        communication_load,
    )
