"""One-computation-per-agent distribution.

Role-equivalent to ``pydcop/distribution/oneagent.py``: the trivial
default mapping — each computation is hosted on its own agent, in order.
Fails if there are fewer agents than computations.  Capacity, hints and
footprint callbacks are ignored, as in the reference.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from pydcop_tpu.distribution.objects import (
    Distribution,
    DistributionHints,
    ImpossibleDistributionException,
)


def distribute(
    computation_graph,
    agentsdef: Iterable,
    hints: Optional[DistributionHints] = None,
    computation_memory: Optional[Callable] = None,
    communication_load: Optional[Callable] = None,
) -> Distribution:
    agents = list(agentsdef)
    nodes = computation_graph.nodes
    if len(agents) < len(nodes):
        raise ImpossibleDistributionException(
            f"oneagent needs at least as many agents as computations: "
            f"{len(agents)} agents < {len(nodes)} computations"
        )
    mapping = {a.name: [] for a in agents}
    for agent, node in zip(agents, nodes):
        mapping[agent.name].append(node.name)
    return Distribution(mapping)


def distribution_cost(
    distribution: Distribution,
    computation_graph,
    agentsdef: Iterable,
    computation_memory: Optional[Callable] = None,
    communication_load: Optional[Callable] = None,
):
    """oneagent optimizes nothing; its cost is always 0 (reference
    behavior)."""
    return 0.0, 0.0, 0.0
