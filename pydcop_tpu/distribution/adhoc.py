"""Greedy capacity-aware distribution with hints.

Role-equivalent to ``pydcop/distribution/adhoc.py``: a fast heuristic
that honors ``DistributionHints`` (``must_host``, ``host_with``) and
agent capacities, and otherwise balances load: hint-pinned computations
are placed first, then each remaining computation group goes to the
agent with the most remaining capacity that can take it.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional

from pydcop_tpu.distribution.objects import (
    Distribution,
    DistributionHints,
    ImpossibleDistributionException,
)


def _footprint(node, computation_memory: Optional[Callable]) -> float:
    if computation_memory is None:
        return 0.0
    return float(computation_memory(node))


def distribute(
    computation_graph,
    agentsdef: Iterable,
    hints: Optional[DistributionHints] = None,
    computation_memory: Optional[Callable] = None,
    communication_load: Optional[Callable] = None,
) -> Distribution:
    agents = list(agentsdef)
    if not agents:
        raise ImpossibleDistributionException("No agents")
    hints = hints or DistributionHints()
    nodes = {n.name: n for n in computation_graph.nodes}
    remaining_cap: Dict[str, float] = {a.name: a.capacity for a in agents}
    placed: Dict[str, str] = {}  # computation -> agent
    mapping: Dict[str, List[str]] = {a.name: [] for a in agents}

    def place(comp: str, agent: str) -> None:
        foot = _footprint(nodes[comp], computation_memory)
        if remaining_cap[agent] < foot:
            raise ImpossibleDistributionException(
                f"Agent {agent} lacks capacity for {comp} "
                f"({remaining_cap[agent]:.1f} < {foot:.1f})"
            )
        remaining_cap[agent] -= foot
        placed[comp] = agent
        mapping[agent].append(comp)

    # 1. must_host pins
    for agent_name, comps in hints.must_host_map.items():
        if agent_name not in mapping:
            raise ImpossibleDistributionException(
                f"must_host references unknown agent {agent_name}"
            )
        for comp in comps:
            if comp in placed:
                if placed[comp] != agent_name:
                    raise ImpossibleDistributionException(
                        f"{comp} must_host on both {placed[comp]} and "
                        f"{agent_name}"
                    )
                continue
            if comp in nodes:
                place(comp, agent_name)

    # 2. host_with groups: any member already placed pins the group
    for comp in list(nodes):
        if comp in placed:
            continue
        group = [c for c in hints.host_with(comp) if c in nodes]
        if not group:
            continue
        anchor = next((placed[c] for c in group if c in placed), None)
        if anchor is not None:
            place(comp, anchor)

    # 3. everything else: largest-footprint first onto the emptiest agent
    loose = sorted(
        (c for c in nodes if c not in placed),
        key=lambda c: -_footprint(nodes[c], computation_memory),
    )
    for comp in loose:
        foot = _footprint(nodes[comp], computation_memory)
        # group mates that must follow this computation
        group = [
            c
            for c in hints.host_with(comp)
            if c in nodes and c not in placed
        ]
        group_foot = foot + sum(
            _footprint(nodes[c], computation_memory) for c in group
        )
        # most remaining capacity, then fewest hosted computations (so
        # zero-footprint problems still spread), then name for determinism
        best = max(
            remaining_cap,
            key=lambda a: (remaining_cap[a], -len(mapping[a]), a),
        )
        if remaining_cap[best] < group_foot:
            raise ImpossibleDistributionException(
                f"No agent has capacity {group_foot:.1f} for {comp} "
                f"and its host_with group"
            )
        place(comp, best)
        for c in group:
            place(c, best)

    return Distribution({a: cs for a, cs in mapping.items()})


def distribution_cost(
    distribution: Distribution,
    computation_graph,
    agentsdef: Iterable,
    computation_memory: Optional[Callable] = None,
    communication_load: Optional[Callable] = None,
):
    from pydcop_tpu.distribution._cost import distribution_cost as _dc

    return _dc(
        distribution,
        computation_graph,
        agentsdef,
        computation_memory,
        communication_load,
    )
