"""Distribution (computation → agent placement) strategies.

Role-equivalent to ``pydcop/distribution/``: each strategy module
exports ``distribute(computation_graph, agentsdef, hints,
computation_memory, communication_load) -> Distribution`` and
``distribution_cost(...) -> (total, comm, hosting)``.
"""

from __future__ import annotations

import importlib
import pkgutil
from typing import List

from pydcop_tpu.distribution.objects import (  # noqa: F401
    Distribution,
    DistributionHints,
    ImpossibleDistributionException,
)

_PACKAGE = "pydcop_tpu.distribution"


def load_distribution_module(name: str):
    """Import a distribution strategy module by name."""
    if name.startswith("_") or name == "objects":
        raise ValueError(f"Unknown distribution method {name!r}")
    try:
        mod = importlib.import_module(f"{_PACKAGE}.{name}")
    except ImportError as e:
        raise ValueError(
            f"Could not load distribution {name!r}: {e}; available: "
            f"{list_available_distributions()}"
        ) from e
    if not hasattr(mod, "distribute"):
        raise ValueError(f"{name!r} is not a distribution method")
    return mod


def compute_distribution(
    distribution,
    graph,
    agent_defs,
    *,
    hints=None,
    algo_module=None,
    computation_memory=None,
    communication_load=None,
) -> Distribution:
    """Run a distribution strategy — the one shared invocation ritual
    (used by the distribute CLI, the dynamic engine, and the host
    runtime, which would otherwise each copy it).

    ``distribution`` is a strategy name or an already-imported
    strategy module.  Footprint callbacks default to the algorithm
    module's ``computation_memory``/``communication_load`` when an
    ``algo_module`` is given; explicit callbacks win.
    """
    mod = (
        load_distribution_module(distribution)
        if isinstance(distribution, str)
        else distribution
    )
    if computation_memory is None and algo_module is not None:
        computation_memory = getattr(
            algo_module, "computation_memory", None
        )
    if communication_load is None and algo_module is not None:
        communication_load = getattr(
            algo_module, "communication_load", None
        )
    return mod.distribute(
        graph,
        agent_defs,
        hints=hints,
        computation_memory=computation_memory,
        communication_load=communication_load,
    )


def list_available_distributions() -> List[str]:
    import pydcop_tpu.distribution as pkg

    names = []
    for info in pkgutil.iter_modules(pkg.__path__):
        if info.name.startswith("_") or info.name == "objects":
            continue
        try:
            mod = importlib.import_module(f"{_PACKAGE}.{info.name}")
        except ImportError:
            continue  # an unimportable strategy must not hide the rest
        if hasattr(mod, "distribute"):
            names.append(info.name)
    return sorted(names)
