"""Distribution (computation → agent placement) strategies.

Role-equivalent to ``pydcop/distribution/``: each strategy module
exports ``distribute(computation_graph, agentsdef, hints,
computation_memory, communication_load) -> Distribution`` and
``distribution_cost(...) -> (total, comm, hosting)``.
"""

from __future__ import annotations

import importlib
import pkgutil
from typing import List

from pydcop_tpu.distribution.objects import (  # noqa: F401
    Distribution,
    DistributionHints,
    ImpossibleDistributionException,
)

_PACKAGE = "pydcop_tpu.distribution"


def load_distribution_module(name: str):
    """Import a distribution strategy module by name."""
    if name.startswith("_") or name == "objects":
        raise ValueError(f"Unknown distribution method {name!r}")
    try:
        mod = importlib.import_module(f"{_PACKAGE}.{name}")
    except ImportError as e:
        raise ValueError(
            f"Could not load distribution {name!r}: {e}; available: "
            f"{list_available_distributions()}"
        ) from e
    if not hasattr(mod, "distribute"):
        raise ValueError(f"{name!r} is not a distribution method")
    return mod


def list_available_distributions() -> List[str]:
    import pydcop_tpu.distribution as pkg

    names = []
    for info in pkgutil.iter_modules(pkg.__path__):
        if info.name.startswith("_") or info.name == "objects":
            continue
        try:
            mod = importlib.import_module(f"{_PACKAGE}.{info.name}")
        except ImportError:
            continue  # an unimportable strategy must not hide the rest
        if hasattr(mod, "distribute"):
            names.append(info.name)
    return sorted(names)
