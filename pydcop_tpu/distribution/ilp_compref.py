"""ILP distribution over computation-memory + communication-load.

Role-equivalent to ``pydcop/distribution/ilp_compref.py``: exact
placement minimizing the weighted sum of communication (edge load ×
route) and hosting costs under capacity constraints — the same
objective ``distribution_cost`` evaluates, solved to optimality.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from pydcop_tpu.distribution._cost import (
    RATIO_HOST_COMM,
    distribution_cost as _dc,
)
from pydcop_tpu.distribution._ilp import solve_ilp_placement
from pydcop_tpu.distribution.objects import Distribution, DistributionHints


def distribute(
    computation_graph,
    agentsdef: Iterable,
    hints: Optional[DistributionHints] = None,
    computation_memory: Optional[Callable] = None,
    communication_load: Optional[Callable] = None,
) -> Distribution:
    return solve_ilp_placement(
        computation_graph,
        agentsdef,
        hints,
        computation_memory,
        communication_load,
        comm_w=1.0,
        hosting_w=RATIO_HOST_COMM,
    )


def distribution_cost(
    distribution,
    computation_graph,
    agentsdef,
    computation_memory=None,
    communication_load=None,
):
    return _dc(
        distribution,
        computation_graph,
        agentsdef,
        computation_memory,
        communication_load,
    )
