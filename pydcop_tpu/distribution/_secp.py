"""Shared SECP-placement pinning logic.

The SECP deployment papers' premise (reference: the ``gh_secp_*`` /
``oilp_secp_*`` modules under ``pydcop/distribution/``): actuator
*variable* computations are physically tied to the device that owns
the actuator — only factor/rule computations are free to place.  The
owner is identified as the unique agent with a zero hosting cost for
the computation (the SECP generator encodes ownership exactly this
way); explicit ``must_host`` hints take precedence.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

from pydcop_tpu.distribution.objects import (
    DistributionHints,
    ImpossibleDistributionException,
)


def secp_pins(
    computation_graph,
    agentsdef: Iterable,
    hints: Optional[DistributionHints],
) -> Dict[str, str]:
    """computation name → pinned agent name for actuator variables."""
    agents = list(agentsdef)
    pins: Dict[str, str] = {}
    if hints is not None:
        for agent_name, comps in hints.must_host_map.items():
            for comp in comps:
                pins[comp] = agent_name

    for node in computation_graph.nodes:
        if node.name in pins:
            continue
        if not _is_variable_node(node):
            continue
        owners = [
            a.name for a in agents if a.hosting_cost(node.name) == 0
        ]
        if len(owners) == 1:
            pins[node.name] = owners[0]
        elif not owners:
            raise ImpossibleDistributionException(
                f"SECP placement: variable computation {node.name!r} "
                "has no owning agent (no agent hosts it at cost 0 and "
                "no must_host hint names it)"
            )
        # several zero-cost agents: genuinely free — leave unpinned
    return pins


def _is_variable_node(node) -> bool:
    """Variable computations are pinned; factor computations are free."""
    return hasattr(node, "variable")
