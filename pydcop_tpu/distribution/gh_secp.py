"""Greedy SECP placement heuristic (reference: the ``gh_secp_*``
distribution modules — fgdp/cgdp variants are covered by the one
``distribute`` since the graph model arrives as an argument).

Actuator variable computations are pinned to their owning device agent
(``_secp.secp_pins``); the remaining factor/rule computations are then
placed greedily by the communication+hosting heuristic, exactly the
``heur_comhost`` rule, but starting from the SECP pinning.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from pydcop_tpu.distribution._cost import (  # noqa: F401  (re-export)
    distribution_cost,
)
from pydcop_tpu.distribution._secp import secp_pins
from pydcop_tpu.distribution.heur_comhost import (
    distribute as _heur_distribute,
)
from pydcop_tpu.distribution.objects import Distribution, DistributionHints


def distribute(
    computation_graph,
    agentsdef: Iterable,
    hints: Optional[DistributionHints] = None,
    computation_memory: Optional[Callable] = None,
    communication_load: Optional[Callable] = None,
) -> Distribution:
    agents = list(agentsdef)
    pins = secp_pins(computation_graph, agents, hints)
    pinned_hints = DistributionHints(
        must_host=_pins_as_must_host(pins),
        host_with=hints.host_with_map if hints is not None else None,
    )
    return _heur_distribute(
        computation_graph,
        agents,
        hints=pinned_hints,
        computation_memory=computation_memory,
        communication_load=communication_load,
    )


def _pins_as_must_host(pins):
    out = {}
    for comp, agent in pins.items():
        out.setdefault(agent, []).append(comp)
    return out
