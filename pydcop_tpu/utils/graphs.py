"""Generic graph helpers (reference: ``pydcop/utils/graphs.py``).

Operates on the *primal constraint graph* of a DCOP — one vertex per
variable, one edge per pair of variables sharing a constraint — which
is what the reference's helpers (cycle detection, diameter, networkx
export) are used for by the graph builders and distribution layer.

All functions accept either a DCOP (its constraints define the edges)
or an explicit adjacency mapping ``{vertex: iterable-of-neighbors}``.
"""

from __future__ import annotations

from collections import deque
from itertools import combinations
from typing import Any, Dict, Hashable, Iterable, List, Mapping, Set


def as_adjacency(graph) -> Dict[Hashable, Set[Hashable]]:
    """Normalize a DCOP or an adjacency mapping to ``{v: set(nbrs)}``,
    symmetrized."""
    if hasattr(graph, "constraints") and hasattr(graph, "variables"):
        adj: Dict[Hashable, Set[Hashable]] = {
            name: set() for name in graph.variables
        }
        for c in graph.constraints.values():
            names = [n for n in c.scope_names if n in adj]
            for a, b in combinations(names, 2):
                adj[a].add(b)
                adj[b].add(a)
        return adj
    adj = {v: set(nbrs) for v, nbrs in graph.items()}
    for v, nbrs in list(adj.items()):
        for n in nbrs:
            adj.setdefault(n, set()).add(v)
    return adj


def has_cycle(graph) -> bool:
    """True iff the (undirected) graph contains a cycle."""
    adj = as_adjacency(graph)
    seen: Set[Hashable] = set()
    for start in adj:
        if start in seen:
            continue
        # BFS forest; a visited non-parent neighbor closes a cycle
        parent: Dict[Hashable, Any] = {start: None}
        queue = deque([start])
        seen.add(start)
        while queue:
            v = queue.popleft()
            for n in adj[v]:
                if n == parent[v]:
                    continue
                if n in seen:
                    return True
                seen.add(n)
                parent[n] = v
                queue.append(n)
    return False


def connected_components(graph) -> List[Set[Hashable]]:
    adj = as_adjacency(graph)
    seen: Set[Hashable] = set()
    comps: List[Set[Hashable]] = []
    for start in adj:
        if start in seen:
            continue
        comp: Set[Hashable] = set()
        queue = deque([start])
        seen.add(start)
        while queue:
            v = queue.popleft()
            comp.add(v)
            for n in adj[v]:
                if n not in seen:
                    seen.add(n)
                    queue.append(n)
        comps.append(comp)
    return comps


def _eccentricity(adj, start) -> int:
    dist = {start: 0}
    queue = deque([start])
    far = 0
    while queue:
        v = queue.popleft()
        for n in adj[v]:
            if n not in dist:
                dist[n] = dist[v] + 1
                far = max(far, dist[n])
                queue.append(n)
    if len(dist) != len(adj):
        raise ValueError(
            "diameter is undefined on a disconnected graph "
            f"({len(connected_components(adj))} components)"
        )
    return far


def graph_diameter(graph) -> int:
    """Longest shortest path (hop count); raises on disconnected input."""
    adj = as_adjacency(graph)
    if not adj:
        return 0
    return max(_eccentricity(adj, v) for v in adj)


def cycles_count(graph) -> int:
    """Independent cycles: |E| - |V| + #components (circuit rank)."""
    adj = as_adjacency(graph)
    n_edges = sum(len(nbrs) for nbrs in adj.values()) // 2
    return n_edges - len(adj) + len(connected_components(adj))


def as_networkx_graph(graph):
    """Export to a ``networkx.Graph`` (used for plotting/analysis)."""
    import networkx as nx

    adj = as_adjacency(graph)
    g = nx.Graph()
    g.add_nodes_from(adj)
    for v, nbrs in adj.items():
        for n in nbrs:
            g.add_edge(v, n)
    return g


def as_bipartite_networkx_graph(dcop):
    """Factor-graph export: variable and constraint vertices with
    bipartite labels (variables 0, constraints 1)."""
    import networkx as nx

    g = nx.Graph()
    for name in dcop.variables:
        g.add_node(name, bipartite=0)
    for cname, c in dcop.constraints.items():
        g.add_node(cname, bipartite=1)
        for vname in c.scope_names:
            g.add_edge(cname, vname)
    return g
