"""Misc small helpers (reference: ``pydcop/utils/various.py``)."""

from __future__ import annotations

import inspect
from typing import Callable, List


def func_args(f: Callable) -> List[str]:
    """Positional/keyword argument names of a callable (the reference
    uses this to discover a cost function's variables)."""
    return [
        p.name
        for p in inspect.signature(f).parameters.values()
        if p.kind
        in (
            inspect.Parameter.POSITIONAL_OR_KEYWORD,
            inspect.Parameter.KEYWORD_ONLY,
            inspect.Parameter.POSITIONAL_ONLY,
        )
    ]


def number_format(n, precision: int = 3) -> str:
    """Compact human formatting: ints stay ints, floats are rounded,
    large magnitudes get engineering suffixes (1.5k, 2.3M)."""
    if isinstance(n, bool) or n is None:
        return str(n)
    try:
        x = float(n)
    except (TypeError, ValueError):
        return str(n)
    if x != x:  # nan
        return "nan"
    for suffix, scale in (("G", 1e9), ("M", 1e6), ("k", 1e3)):
        if abs(x) >= scale:
            return f"{x / scale:.{precision}g}{suffix}"
    if x == int(x):
        return str(int(x))
    return f"{x:.{precision}g}"


def elapsed_str(seconds: float) -> str:
    """``1h 02m 03s`` style duration formatting for logs/metrics.

    Sub-second durations render as milliseconds (``123ms``) — the old
    seconds form printed telemetry-scale spans as ``0h 00m 00s``-style
    noise.  Negative durations raise: a caller holding one has a clock
    bug (mixed epochs, reversed subtraction) that silent clamping to
    ``0ms`` would bury.
    """
    seconds = float(seconds)
    if seconds < 0:
        raise ValueError(
            f"elapsed_str: negative duration {seconds!r} (mixed clock "
            "epochs or a reversed subtraction?)"
        )
    if seconds < 1.0:
        ms = round(seconds * 1000)
        if ms < 1000:  # 0.9996 rounds to 1000ms — report as seconds
            return f"{ms}ms"
    h, rem = divmod(int(seconds), 3600)
    m, s = divmod(rem, 60)
    if h:
        return f"{h}h {m:02d}m {s:02d}s"
    if m:
        return f"{m}m {s:02d}s"
    return f"{seconds:.3g}s"
