"""Simple-repr serialization.

Turns objects into nested dicts of primitives (and back) so that every
message, problem object and result can be round-tripped through JSON/YAML.
This is the wire format for the host-side control plane, exactly the role
``pydcop/utils/simple_repr.py`` plays in the reference; the design here is
independent (introspection of ``__init__`` parameters against attributes,
with a ``_repr_excluded``/mapping override hook).

On the TPU compute path nothing is serialized per-message — device arrays
never go through this layer — so this module is deliberately plain Python.
"""

from __future__ import annotations

import importlib
import inspect
from typing import Any

# Dict key carrying the qualified class name in a serialized object.
_CLASS_KEY = "__qualified_name__"
_MODULE_KEY = "__module__"


class SimpleReprException(Exception):
    pass


def _is_primitive(o: Any) -> bool:
    return o is None or isinstance(o, (bool, int, float, str))


class SimpleRepr:
    """Mixin providing ``_simple_repr()`` / ``_from_repr()``.

    The default implementation introspects the constructor: for each
    parameter ``p`` of ``__init__``, the instance must expose an attribute
    ``p`` or ``_p`` whose value is itself simple-representable.  Subclasses
    with non-trivial constructors can override ``_simple_repr`` /
    ``_from_repr`` or set ``_repr_mapping`` ({param_name: attr_name}).
    """

    _repr_mapping: dict = {}

    def _simple_repr(self) -> dict:
        r: dict = {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
        }
        sig = inspect.signature(type(self).__init__)
        for name, param in sig.parameters.items():
            if name == "self":
                continue
            if param.kind in (
                inspect.Parameter.VAR_POSITIONAL,
                inspect.Parameter.VAR_KEYWORD,
            ):
                continue
            attr = self._repr_mapping.get(name, name)
            if hasattr(self, attr):
                val = getattr(self, attr)
            elif hasattr(self, "_" + attr):
                val = getattr(self, "_" + attr)
            else:
                raise SimpleReprException(
                    f"Cannot build simple repr for {type(self).__name__}: "
                    f"no attribute for constructor parameter {name!r}"
                )
            r[name] = simple_repr(val)
        return r

    @classmethod
    def _from_repr(cls, r: dict):
        args = {
            k: from_repr(v)
            for k, v in r.items()
            if k not in (_CLASS_KEY, _MODULE_KEY)
        }
        return cls(**args)


def simple_repr(o: Any) -> Any:
    """Return a nested structure of primitives representing ``o``."""
    if _is_primitive(o):
        return o
    if isinstance(o, (list, tuple, set, frozenset)):
        kind = {
            list: "list",
            tuple: "tuple",
            set: "set",
            frozenset: "frozenset",
        }[type(o)]
        items = [simple_repr(i) for i in o]
        if kind == "list":
            return items
        return {_CLASS_KEY: kind, "items": items}
    if isinstance(o, dict):
        # JSON only supports string keys; keep primitives as-is and tag.
        return {
            _CLASS_KEY: "dict",
            "items": [[simple_repr(k), simple_repr(v)] for k, v in o.items()],
        }
    if isinstance(o, SimpleRepr):
        return o._simple_repr()
    # numpy / jax scalars and arrays → python lists (host control plane only)
    if hasattr(o, "tolist"):
        return {_CLASS_KEY: "array", "items": o.tolist()}
    raise SimpleReprException(
        f"Cannot build a simple repr for object of type {type(o)}: {o!r}"
    )


def from_repr(r: Any) -> Any:
    """Rebuild an object from its simple repr."""
    if _is_primitive(r):
        return r
    if isinstance(r, list):
        return [from_repr(i) for i in r]
    if isinstance(r, dict):
        qn = r.get(_CLASS_KEY)
        if qn is None:
            # plain mapping (e.g. parsed YAML) — rebuild values
            return {k: from_repr(v) for k, v in r.items()}
        if qn == "dict":
            return {from_repr(k): from_repr(v) for k, v in r["items"]}
        if qn in ("tuple", "set", "frozenset"):
            ctor = {"tuple": tuple, "set": set, "frozenset": frozenset}[qn]
            return ctor(from_repr(i) for i in r["items"])
        if qn == "array":
            import numpy as np

            return np.asarray(r["items"])
        module = importlib.import_module(r[_MODULE_KEY])
        cls = module
        for part in qn.split("."):
            cls = getattr(cls, part)
        if not (inspect.isclass(cls) and issubclass(cls, SimpleRepr)):
            raise SimpleReprException(
                f"{qn} in {r[_MODULE_KEY]} is not a SimpleRepr class"
            )
        return cls._from_repr(r)
    raise SimpleReprException(f"Cannot rebuild object from repr {r!r}")
