from pydcop_tpu.utils.simple_repr import (
    SimpleRepr,
    SimpleReprException,
    simple_repr,
    from_repr,
)
from pydcop_tpu.utils.expressionfunction import ExpressionFunction

__all__ = [
    "SimpleRepr",
    "SimpleReprException",
    "simple_repr",
    "from_repr",
    "ExpressionFunction",
]
