"""Cost functions defined as Python expression strings.

Role-equivalent to ``pydcop/utils/expressionfunction.py`` in the reference:
wrap an expression like ``"10 if v1 == v2 else 0"`` as a callable whose
free variables are discovered from the AST, with support for fixing some
variables (partial application).

Design notes (TPU build): expression functions only run on the host, at
*compile* time — the problem compiler tabulates them over their (finite)
domains into dense cost tables that live on device.  They are never traced
by JAX, so arbitrary Python is fine here.
"""

from __future__ import annotations

import ast
import re
from typing import Any, Callable, Dict, Iterable, Optional

from pydcop_tpu.utils.simple_repr import SimpleRepr

# Names usable inside expressions without being treated as variables.
_SAFE_BUILTINS = {
    "abs": abs,
    "min": min,
    "max": max,
    "round": round,
    "len": len,
    "sum": sum,
    "int": int,
    "float": float,
    "str": str,
    "bool": bool,
    "pow": pow,
    "all": all,
    "any": any,
    "sorted": sorted,
}


def _is_statement_form(expression: str) -> bool:
    """True when the expression is a function body containing a ``return``
    statement rather than a single expression.  A word-boundary match
    avoids misclassifying names like ``return_delay``; a final AST check
    avoids misclassifying e.g. string literals containing ``return``."""
    if not re.search(r"\breturn\b", expression):
        return False
    try:
        ast.parse(expression, mode="eval")
        return False  # parses as a plain expression → not a body
    except SyntaxError:
        return True


def _free_variables(expression: str) -> set:
    """Names loaded by the expression minus builtins/imports and
    names assigned within (multi-line expressions with 'return')."""
    src = expression
    if _is_statement_form(expression):
        # multi-line function body form
        tree = ast.parse(_as_function_src(expression))
    else:
        tree = ast.parse(src, mode="eval")
    loaded, stored = set(), set()
    imported = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                loaded.add(node.id)
            else:
                stored.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                imported.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.comprehension):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    stored.add(t.id)
    return loaded - stored - imported - set(_SAFE_BUILTINS) - {"math"}


def _as_function_src(expression: str, name: str = "_expr_fn") -> str:
    body = "\n".join("    " + line for line in expression.splitlines())
    return f"def {name}():\n{body}\n"


class ExpressionFunction(SimpleRepr):
    """A callable built from a Python expression string.

    >>> f = ExpressionFunction('a + b')
    >>> sorted(f.variable_names)
    ['a', 'b']
    >>> f(a=1, b=2)
    3

    Supports multi-line bodies containing ``return`` and partial
    application (fixed variables) via ``partial`` / constructor kwargs.
    """

    def __init__(self, expression: str, **fixed_vars: Any):
        self._expression = expression
        self._fixed_vars = dict(fixed_vars)
        self._all_vars = frozenset(_free_variables(expression))
        unknown = set(fixed_vars) - self._all_vars
        if unknown:
            raise ValueError(
                f"Fixed variables {unknown} do not appear in expression "
                f"{expression!r}"
            )
        self._compile()

    def _compile(self) -> None:
        import math

        glb: Dict[str, Any] = {"__builtins__": _SAFE_BUILTINS, "math": math}
        if _is_statement_form(self._expression):
            src = _as_function_src(self._expression)
            code = compile(ast.parse(src), "<expression_function>", "exec")
            # Free variables are injected by re-exec'ing the def with the
            # call scope merged into globals, then calling the function.
            def call(scope: Dict[str, Any]) -> Any:
                g = dict(glb)
                g.update(scope)
                loc2: Dict[str, Any] = {}
                exec(code, g, loc2)
                return loc2["_expr_fn"]()

            self._call = call
        else:
            code = compile(
                ast.parse(self._expression, mode="eval"),
                "<expression_function>",
                "eval",
            )

            def call(scope: Dict[str, Any]) -> Any:
                g = dict(glb)
                g.update(scope)
                return eval(code, g)  # noqa: S307 — sandboxed builtins

            self._call = call

    # -- public API ----------------------------------------------------

    @property
    def expression(self) -> str:
        return self._expression

    @property
    def variable_names(self) -> Iterable[str]:
        """Names of the (non-fixed) variables of the function."""
        return self._all_vars - set(self._fixed_vars)

    @property
    def fixed_vars(self) -> Dict[str, Any]:
        return dict(self._fixed_vars)

    def partial(self, **kwargs: Any) -> "ExpressionFunction":
        fixed = dict(self._fixed_vars)
        fixed.update(kwargs)
        return ExpressionFunction(self._expression, **fixed)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        if args:
            if len(args) == 1 and isinstance(args[0], dict) and not kwargs:
                kwargs = args[0]
            else:
                raise TypeError(
                    "ExpressionFunction must be called with keyword "
                    "arguments (or a single assignment dict)"
                )
        scope = dict(self._fixed_vars)
        scope.update(kwargs)
        missing = set(self._all_vars) - set(scope)
        if missing:
            raise TypeError(f"Missing variable(s) {missing} for {self}")
        return self._call(scope)

    def __repr__(self) -> str:
        return f"ExpressionFunction({self._expression!r})"

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, ExpressionFunction)
            and other._expression == self._expression
            and other._fixed_vars == self._fixed_vars
        )

    def __hash__(self) -> int:
        return hash((self._expression, tuple(sorted(self._fixed_vars.items()))))

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY, simple_repr

        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "expression": self._expression,
            "fixed_vars": simple_repr(self._fixed_vars),
        }

    @classmethod
    def _from_repr(cls, r: dict):
        from pydcop_tpu.utils.simple_repr import from_repr

        fixed = from_repr(r.get("fixed_vars", {})) or {}
        return cls(r["expression"], **fixed)
