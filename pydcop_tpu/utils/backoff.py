"""Shared retry/backoff helper: every reconnect loop in the codebase
(host agent control-plane connect, TCP message-plane writer resend,
the supervised device-dispatch retry path) goes through this one
implementation, so backoff policy — exponential growth, cap, jitter —
is tuned in exactly one place.

Jitter is deterministic on demand, two ways:

- ``seed=`` alone draws the jitter stream from a private
  ``random.Random(seed)`` — reproducible for a single caller, but two
  loops sharing one seed perturb each other's schedules the moment
  their draws interleave.
- ``key=`` (with an optional ``seed``) switches to the *keyed hash*
  variant: the jitter of attempt ``k`` is a pure blake2b hash of
  ``(seed, key, k)`` — the exact determinism contract of
  ``pydcop_tpu.faults.plan.FaultPlan`` decisions.  No shared stream,
  no iteration-order dependence: the host-agent connect loop, every
  TCP writer, and the device supervisor each pass their own key, so a
  chaos replay reproduces every loop's retry timing bit-for-bit no
  matter how the threads interleave.
"""

from __future__ import annotations

import hashlib
import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type, TypeVar

T = TypeVar("T")


def _hashed_unit(seed: int, key: str, attempt: int) -> float:
    """Uniform [0, 1) from a keyed hash — same construction as
    ``faults.plan._u``: the value depends on nothing but its
    arguments, so schedules replay exactly and distinct keys are
    decorrelated."""
    h = hashlib.blake2b(
        f"{seed}|{key}|{attempt}|backoff".encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big") / 2.0**64


def backoff_delays(
    base: float = 0.1,
    factor: float = 2.0,
    max_delay: float = 5.0,
    jitter: float = 0.25,
    seed: Optional[int] = None,
    key: Optional[str] = None,
) -> Iterator[float]:
    """Yield an infinite stream of sleep delays: ``base`` growing by
    ``factor`` up to ``max_delay``, each stretched by a jitter factor
    in ``[1, 1 + jitter]`` (full-jitter would allow 0-sleeps, which
    turn a retry loop into a busy spin against a dead peer).

    With ``key`` given, attempt ``k``'s jitter is the pure hash of
    ``(seed or 0, key, k)`` (module docstring) — stateless and
    per-caller reproducible; without it, jitter comes from a private
    ``random.Random(seed)`` stream (decorrelated across callers when
    ``seed`` is None)."""
    delay = base
    if key is not None:
        s = 0 if seed is None else seed
        attempt = 0
        while True:
            attempt += 1
            yield delay * (1.0 + jitter * _hashed_unit(s, key, attempt))
            delay = min(delay * factor, max_delay)
    rnd = random.Random(seed)
    while True:
        yield delay * (1.0 + jitter * rnd.random())
        delay = min(delay * factor, max_delay)


def call_with_backoff(
    fn: Callable[[], T],
    retry_for: float,
    exceptions: Tuple[Type[BaseException], ...] = (OSError,),
    base: float = 0.1,
    factor: float = 2.0,
    max_delay: float = 5.0,
    jitter: float = 0.25,
    seed: Optional[int] = None,
    key: Optional[str] = None,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    giving_up: Optional[Callable[[], bool]] = None,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
) -> T:
    """Call ``fn`` until it succeeds or ``retry_for`` seconds elapse.

    The LAST failure is re-raised once the deadline passes (never a
    synthetic timeout error: the caller diagnoses from the real one).
    ``giving_up`` is polled before each sleep — a closing transport
    aborts the retry loop early by returning True, re-raising the
    current failure instead of sleeping toward a deadline nobody is
    waiting on.  Sleeps never overshoot the deadline: the final attempt
    happens AT the deadline, not ``max_delay`` past it.  ``key``
    selects the keyed deterministic jitter (module docstring).
    ``on_retry(attempt, error)`` fires once per retry, after the
    decision to keep going and before the sleep — the seam callers use
    to count retries (e.g. the service client's
    ``service.client_retries``) without wrapping ``fn``.
    """
    deadline = clock() + retry_for
    for attempt, delay in enumerate(
        backoff_delays(
            base=base, factor=factor, max_delay=max_delay,
            jitter=jitter, seed=seed, key=key,
        ),
        start=1,
    ):
        try:
            return fn()
        except exceptions as e:
            remaining = deadline - clock()
            if remaining <= 0 or (giving_up is not None and giving_up()):
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(min(delay, remaining))
    raise AssertionError("unreachable")  # pragma: no cover
