"""Shared retry/backoff helper: every reconnect loop in the codebase
(host agent control-plane connect, TCP message-plane writer resend,
chaos-layer probes) goes through this one implementation, so backoff
policy — exponential growth, cap, jitter — is tuned in exactly one
place.

Jitter is seedable: the fault-injection harness (``pydcop_tpu.faults``)
replays runs, so a retry schedule must be reproducible when a seed is
given (and decorrelated across callers when it is not).
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type, TypeVar

T = TypeVar("T")


def backoff_delays(
    base: float = 0.1,
    factor: float = 2.0,
    max_delay: float = 5.0,
    jitter: float = 0.25,
    seed: Optional[int] = None,
) -> Iterator[float]:
    """Yield an infinite stream of sleep delays: ``base`` growing by
    ``factor`` up to ``max_delay``, each stretched by a random factor
    in ``[1, 1 + jitter]`` (full-jitter would allow 0-sleeps, which
    turn a retry loop into a busy spin against a dead peer)."""
    rnd = random.Random(seed)
    delay = base
    while True:
        yield delay * (1.0 + jitter * rnd.random())
        delay = min(delay * factor, max_delay)


def call_with_backoff(
    fn: Callable[[], T],
    retry_for: float,
    exceptions: Tuple[Type[BaseException], ...] = (OSError,),
    base: float = 0.1,
    factor: float = 2.0,
    max_delay: float = 5.0,
    jitter: float = 0.25,
    seed: Optional[int] = None,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    giving_up: Optional[Callable[[], bool]] = None,
) -> T:
    """Call ``fn`` until it succeeds or ``retry_for`` seconds elapse.

    The LAST failure is re-raised once the deadline passes (never a
    synthetic timeout error: the caller diagnoses from the real one).
    ``giving_up`` is polled before each sleep — a closing transport
    aborts the retry loop early by returning True, re-raising the
    current failure instead of sleeping toward a deadline nobody is
    waiting on.  Sleeps never overshoot the deadline: the final attempt
    happens AT the deadline, not ``max_delay`` past it.
    """
    deadline = clock() + retry_for
    for delay in backoff_delays(
        base=base, factor=factor, max_delay=max_delay, jitter=jitter,
        seed=seed,
    ):
        try:
            return fn()
        except exceptions:
            remaining = deadline - clock()
            if remaining <= 0 or (giving_up is not None and giving_up()):
                raise
            sleep(min(delay, remaining))
    raise AssertionError("unreachable")  # pragma: no cover
