"""``pydcop_tpu fleet`` — the self-healing replicated serving fleet.

Spawns N ``serve`` replica subprocesses (or attaches to externally
started ones by address), wires each replica's k standby successors
from the hash ring (``engine/fleet.py``), and fronts them with a
:class:`~pydcop_tpu.engine.fleet.FleetRouter` speaking the ordinary
newline-JSON wire protocol — existing
:class:`~pydcop_tpu.engine.service.ServiceClient` code points at the
router unchanged.  Sessions pin to a replica by hash of their name;
each replica streams its session delta logs to its ring successors,
so a SIGKILL'd replica's sessions resume on the standby
``compile.incremental``-only, and a failover retry of an answered
request replays the replicated reply (exactly-once).  See
``docs/serving.md``, "The fleet".

Chaos: ``--chaos replica_kill=T[:IDX]`` SIGKILLs one spawned replica
T seconds after the fleet is up — the victim chosen by a pure hash of
the seed unless pinned with ``:IDX`` — under the same determinism
contract as every other fault kind (``docs/faults.md``).  Fleet-level
kinds only: message/schedule/device/wire clauses belong to the layers
that inject them and are rejected here.

Prints one JSON head line ``{"fleet": "host:port", "replicas":
{name: addr, ...}, "pid": N}`` once the router is bound.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading

from pydcop_tpu.commands._common import add_trace_arguments


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "fleet",
        help="run a replicated serving fleet: a consistent-hash "
        "router in front of N serve replicas with k-resilient "
        "session replication and exactly-once failover "
        "(docs/serving.md)",
    )
    p.add_argument(
        "--replicas", type=int, default=2, metavar="N",
        help="number of serve replica subprocesses to spawn on this "
        "host (ignored with --attach); default 2",
    )
    p.add_argument(
        "--attach", action="append", default=None, metavar="ADDR",
        dest="attach",
        help="front an EXTERNALLY started serve replica at ADDR "
        "(host:port, repeatable) instead of spawning; pair with "
        "ADDR=host:port/metrics_host:metrics_port to give the "
        "health watcher its /healthz endpoint",
    )
    p.add_argument(
        "--host", default="127.0.0.1",
        help="router bind address (default 127.0.0.1)",
    )
    p.add_argument(
        "--port", type=int, default=9009,
        help="router listen port (0 = ephemeral; printed on the "
        "head line)",
    )
    p.add_argument(
        "--resilience", type=int, default=1, metavar="K",
        help="standbys per replica (each replica streams its "
        "session delta logs to its K ring successors); default 1",
    )
    p.add_argument(
        "--pad_policy", default="pow2", metavar="POLICY",
        help="shape-bucketing policy passed to every spawned "
        "replica (must match across the fleet — a failed-over "
        "session must land in the same shape bucket); default pow2",
    )
    p.add_argument(
        "--max_batch", type=int, default=32, metavar="K",
        help="per-replica tick policy: dispatch at K pending",
    )
    p.add_argument(
        "--max_wait", type=float, default=0.01, metavar="SECONDS",
        help="per-replica tick policy: max queue hold",
    )
    p.add_argument(
        "--compile_cache", default=None, metavar="DIR",
        help="persistent XLA compilation cache DIR shared by every "
        "spawned replica (docs/performance.md)",
    )
    p.add_argument(
        "--session_checkpoint", default=None, metavar="DIR",
        help="per-replica session checkpoint directory (each "
        "replica derives sessions-<pid>.json inside it)",
    )
    p.add_argument(
        "--flight_dump", default=None, metavar="DIR",
        help="per-replica flight-recorder dump directory (each "
        "replica derives flight-<pid>.json inside it)",
    )
    p.add_argument(
        "--health_interval", type=float, default=0.25,
        metavar="SECONDS",
        help="router /healthz poll interval — the detection half of "
        "the failover budget; default 0.25s",
    )
    p.add_argument(
        "--metrics_port", type=int, default=None, metavar="PORT",
        help="serve the ROUTER's aggregate /metrics and /healthz "
        "(fleet status + per-replica roster with their metrics "
        "addresses) on this port; `pydcop_tpu top` expands the "
        "roster into per-replica rows (docs/observability.md)",
    )
    p.add_argument(
        "--chaos", default=None, metavar="SPEC",
        help="fleet-level seeded chaos: replica_kill=T[:IDX] "
        "SIGKILLs one spawned replica T seconds after startup "
        "(victim = pure hash of the seed, or pinned by :IDX) — "
        "docs/faults.md.  Message/schedule/device/wire kinds are "
        "rejected here (inject them at their own layers)",
    )
    p.add_argument(
        "--chaos_seed", type=int, default=0,
        help="seed for the --chaos fault plan (determinism/replay)",
    )
    add_trace_arguments(p)
    p.set_defaults(func=run_cmd)


def _parse_attach(specs):
    """``--attach`` values → ordered (name, addr, metrics) tuples.
    ``host:port`` alone leaves the health watcher blind to that
    replica (forward-failure detection still applies);
    ``host:port/mhost:mport`` names its /healthz endpoint."""
    out = []
    for i, spec in enumerate(specs):
        addr, _, metrics = spec.partition("/")
        if ":" not in addr:
            raise SystemExit(
                f"fleet: --attach {spec!r} is not host:port"
                "[/metrics_host:metrics_port]"
            )
        out.append((f"r{i}", addr, metrics or None))
    return out


def _spawn_replicas(args, n):
    """Spawn N ``serve --port 0 --metrics_port 0`` subprocesses and
    parse each head JSON line for its wire + metrics addresses.
    A replica that dies during startup surfaces its stderr as a
    structured error instead of a hang — a broken ``--resume``
    checkpoint in a shared config must fail the fleet loudly."""
    env = dict(os.environ)
    procs = []
    replicas = []
    base = [
        sys.executable, "-m", "pydcop_tpu", "serve",
        "--port", "0", "--metrics_port", "0",
        "--pad_policy", args.pad_policy,
        "--max_batch", str(args.max_batch),
        "--max_wait", str(args.max_wait),
    ]
    if args.compile_cache:
        base += ["--compile_cache", args.compile_cache]
    if args.session_checkpoint:
        base += ["--session_checkpoint", args.session_checkpoint]
    if args.flight_dump:
        base += ["--flight_dump", args.flight_dump]
    for i in range(n):
        proc = subprocess.Popen(
            base, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, env=env, text=True,
        )
        procs.append(proc)
    for i, proc in enumerate(procs):
        line = proc.stdout.readline()
        if not line:
            err = (proc.stderr.read() or "").strip()
            for p in procs:
                p.kill()
            raise SystemExit(
                f"fleet: replica r{i} (pid {proc.pid}) died during "
                f"startup: {err.splitlines()[-1] if err else 'no output'}"
            )
        head = json.loads(line)
        replicas.append(
            (f"r{i}", head["serving"], head.get("metrics"))
        )
        # drain the pipes forever after: a replica must never block
        # on a full stderr buffer writing its drain-time stats line
        for stream in (proc.stdout, proc.stderr):
            t = threading.Thread(
                target=_drain, args=(stream,), daemon=True
            )
            t.start()
    return procs, replicas


def _drain(stream) -> None:
    try:
        for _ in stream:
            pass
    except (OSError, ValueError):
        pass


def _wire_standbys(replicas, k):
    """Send each replica its ring-successor standby addresses (the
    ``standby`` wire op) — the replication chain the router's
    failover rule walks."""
    from pydcop_tpu.engine.fleet import standby_map
    from pydcop_tpu.engine.service import ServiceClient

    addr_of = {name: addr for name, addr, _ in replicas}
    smap = standby_map(list(addr_of), k=k)
    for name, succs in smap.items():
        with ServiceClient(addr_of[name], timeout=10.0) as cli:
            cli._call(
                "standby",
                standbys=[addr_of[s] for s in succs],
            )
    return smap


def run_cmd(args) -> int:
    from pydcop_tpu.engine.fleet import FleetRouter, Replica
    from pydcop_tpu.telemetry import get_metrics, session

    if args.resilience < 1:
        raise SystemExit("fleet: --resilience must be >= 1")

    plan = None
    if args.chaos:
        from pydcop_tpu.faults import FaultPlan, FaultSpecError

        try:
            plan = FaultPlan.from_spec(args.chaos, args.chaos_seed)
        except FaultSpecError as e:
            raise SystemExit(f"fleet: {e}")
        # fleet accepts ONLY the fleet category; every other
        # category has its own injection layer and its own flag
        if plan.message_faults_configured or plan.crashes:
            raise SystemExit(
                "fleet: message/schedule chaos kinds inject at the "
                "agent message plane — use `pydcop_tpu run/agent "
                "--chaos` (docs/faults.md)"
            )
        if plan.device_faults_configured:
            raise SystemExit(
                "fleet: device chaos kinds inject at each replica's "
                "dispatch seam — use `pydcop_tpu serve --chaos` "
                "(docs/faults.md)"
            )
        if plan.wire_faults_configured:
            raise SystemExit(
                "fleet: wire chaos kinds inject in each replica's "
                "frame loop — use `pydcop_tpu serve --chaos` "
                "(docs/faults.md)"
            )
        if not plan.fleet_faults_configured:
            plan = None

    attach = _parse_attach(args.attach) if args.attach else None
    if plan is not None and attach is not None:
        raise SystemExit(
            "fleet: replica_kill needs spawned replicas (--replicas "
            "N) — the fleet does not own attached processes"
        )
    if attach is None and args.replicas < 1:
        raise SystemExit("fleet: --replicas must be >= 1")

    with session(args.trace, args.trace_format):
        procs = []
        router = None
        exporter = None
        killer = None
        prev_term = None
        try:
            if attach is not None:
                replicas = attach
            else:
                procs, replicas = _spawn_replicas(
                    args, args.replicas
                )
            _wire_standbys(replicas, args.resilience)
            router = FleetRouter(
                [Replica(*r) for r in replicas],
                host=args.host,
                port=args.port,
                health_interval=args.health_interval,
            )
            if args.metrics_port is not None:
                from pydcop_tpu.telemetry.export import (
                    MetricsExporter,
                )

                exporter = MetricsExporter(
                    get_metrics().snapshot,
                    router.health,
                    host=args.host,
                    port=args.metrics_port,
                )
            if plan is not None:
                decision = plan.decide_replica_kill(len(replicas))
                if decision is not None:
                    delay, victim = decision
                    pid = procs[victim].pid

                    def _kill():
                        met = get_metrics()
                        if met.enabled:
                            met.inc("fleet.replica_killed")
                        try:
                            os.kill(pid, signal.SIGKILL)
                        except OSError:
                            pass

                    killer = threading.Timer(delay, _kill)
                    killer.daemon = True
                    killer.start()
            prev_term = signal.signal(
                signal.SIGTERM,
                lambda *_: router.request_shutdown(),
            )
            head = {
                "fleet": "%s:%d" % router.address,
                "pid": os.getpid(),
                "replicas": {
                    name: addr for name, addr, _ in replicas
                },
            }
            if exporter is not None:
                head["metrics"] = "%s:%d" % exporter.address
            print(json.dumps(head), flush=True)
            try:
                router.wait(args.timeout)
            except KeyboardInterrupt:
                pass
        finally:
            if killer is not None:
                killer.cancel()
            if router is not None:
                router.close()
            if exporter is not None:
                exporter.close()
            if prev_term is not None:
                signal.signal(signal.SIGTERM, prev_term)
            # graceful replica drain: TERM (the replicas' own drain
            # funnel writes checkpoints / final stats), then reap
            for proc in procs:
                if proc.poll() is None:
                    proc.terminate()
            for proc in procs:
                try:
                    proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    proc.kill()
            if router is not None:
                stats = router.stats()
                print(
                    json.dumps({"fleet_stats": stats}, default=str),
                    file=sys.stderr,
                    flush=True,
                )
    return 0
