"""``pydcop_tpu worker`` — internal: one elastic-runtime SPMD worker.

Spawned by the elastic orchestrator/agent supervisors
(``infrastructure/elastic.py``); not intended for direct use.  The
worker connects to the orchestrator's control port, receives its
epoch's deployment, joins the ``jax.distributed`` cluster, and solves
in lockstep until the epoch ends (result/halt) or is killed by its
supervisor at a reform.
"""

from __future__ import annotations


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "worker",
        help="internal: elastic-runtime SPMD worker (spawned by the "
        "elastic orchestrator/agent, see orchestrator --elastic)",
    )
    p.add_argument("--orchestrator", required=True, metavar="HOST:PORT")
    p.add_argument("--epoch", type=int, required=True)
    p.add_argument("--process-id", type=int, required=True)
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.infrastructure.elastic import run_worker

    return run_worker(args.orchestrator, args.epoch, args.process_id)
