"""``pydcop_tpu serve`` — the continuous-batching solver service.

Starts a resident :class:`~pydcop_tpu.engine.service.SolverService`
behind a TCP :class:`~pydcop_tpu.engine.service.ServiceServer`
(newline-JSON frames, ``docs/serving.md``) and serves until a client
sends ``shutdown``, the global ``-t/--timeout`` elapses, Ctrl-C, or
SIGTERM.

Every exit path is a **graceful drain**: new admissions are rejected,
in-flight ticks finish and deliver, the final session checkpoint is
written (``--session_checkpoint``), and the final JSON stats report —
including the zeroed queue-depth gauge — is emitted on stderr.  A
restarted ``serve --resume`` replays the checkpointed sessions through
the :class:`~pydcop_tpu.engine.incremental.IncrementalCompiler`, so
reconnecting clients' ``set_values`` follow-ups stay
``compile.incremental``-only.

Prints one JSON line ``{"serving": "host:port", "pid": N}`` once the
socket is bound (a parent process can parse it to find an ephemeral
``--port 0``).
"""

from __future__ import annotations

import json
import signal
import sys

from pydcop_tpu.commands._common import (
    add_supervisor_arguments,
    add_trace_arguments,
)


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "serve",
        help="run a resident continuous-batching solver service: an "
        "admission queue coalesces concurrent solve requests into "
        "shape buckets and dispatches merged groups per tick on warm "
        "compiled executables (docs/serving.md)",
    )
    p.add_argument(
        "--host", default="127.0.0.1",
        help="bind address (default 127.0.0.1 — loopback only)",
    )
    p.add_argument(
        "--port", type=int, default=9010,
        help="listen port (0 = ephemeral; the bound port is printed "
        "on the serving line)",
    )
    p.add_argument(
        "--pad_policy", default="pow2", metavar="POLICY",
        help="shape-bucketing policy applied to every request "
        "('pow2' / 'pow2:<floor>' / 'none'): what steers "
        "similarly-sized problems into shared executables and "
        "coalesced dispatches (docs/performance.md); default: pow2",
    )
    p.add_argument(
        "--max_batch", type=int, default=32, metavar="K",
        help="tick policy: dispatch as soon as K requests are "
        "pending (also the per-tick drain cap); default 32",
    )
    p.add_argument(
        "--max_wait", type=float, default=0.01, metavar="SECONDS",
        help="tick policy: never hold the oldest pending request "
        "longer than this before dispatching (the queue-wait bound "
        "behind the service's p99); default 0.01",
    )
    p.add_argument(
        "--instance_bucket", choices=["pow2", "none"], default="pow2",
        help="pad coalesced groups to power-of-two occupancy so the "
        "vmapped runner cache converges on a handful of executables "
        "(steady-state ticks then do ZERO XLA compiles); default pow2",
    )
    p.add_argument(
        "--compile_cache", default=None, metavar="DIR",
        help="persist XLA executables to DIR (jax compilation "
        "cache): a restarted service skips backend compilation of "
        "programs any previous process built (docs/performance.md)",
    )
    p.add_argument(
        "--max_queue", type=int, default=1024, metavar="N",
        help="bounded admission queue: requests arriving at depth N "
        "are rejected immediately with status='shed' instead of "
        "growing the queue without limit; deadline-carrying requests "
        "the service already knows it cannot meet are shed too "
        "(docs/serving.md); default 1024",
    )
    p.add_argument(
        "--max_inflight", type=int, default=8, metavar="N",
        help="per-connection in-flight request cap (wire "
        "backpressure): a client pipelining past it is answered "
        "status='shed'; default 8",
    )
    p.add_argument(
        "--session_memo_bytes", type=int, default=64 << 20,
        metavar="BYTES",
        help="per-session byte bound of the subtree-fingerprint "
        "message memo behind exact-algorithm (dpop) session "
        "follow-ups: a set_values delta re-contracts only the dirty "
        "root-to-changed-constraint path, zero XLA compiles warm "
        "(docs/performance.md, 'O(delta) re-solves'); 0 disables "
        "memoization; default 64 MiB",
    )
    p.add_argument(
        "--session_checkpoint", default=None, metavar="PATH",
        help="write the final session checkpoint (pinned dcops, "
        "applied set_values deltas, per-session counters) to PATH on "
        "every exit path — SIGTERM/Ctrl-C/shutdown all drain "
        "gracefully first (docs/serving.md).  When PATH is a "
        "directory, a per-process file sessions-<port|pid>.json is "
        "derived inside it, so fleet replicas sharing one config "
        "never clobber each other's checkpoints",
    )
    p.add_argument(
        "--resume", action="store_true",
        help="replay the --session_checkpoint file at startup: "
        "restored sessions' set_values follow-ups stay "
        "compile.incremental-only, bit-identical to an undisturbed "
        "service.  A missing, truncated, or schema-drifted "
        "checkpoint fails with a structured error (exit, not a "
        "silently-empty service)",
    )
    p.add_argument(
        "--standby", action="append", default=None, metavar="ADDR",
        dest="standbys",
        help="stream every session's delta log to the replica at "
        "ADDR (host:port; repeatable for k-resilience) as it "
        "mutates, so a kill of THIS process resumes its sessions "
        "there compile.incremental-only — `pydcop_tpu fleet` wires "
        "these automatically from the hash ring (docs/serving.md)",
    )
    p.add_argument(
        "--metrics_port", type=int, default=None, metavar="PORT",
        help="serve GET /metrics (Prometheus text exposition of the "
        "full registry) and GET /healthz (queue depth, in-flight, "
        "drain state) on this port (0 = ephemeral; the bound port is "
        "printed on the serving line as \"metrics\"); poll it live "
        "with `pydcop_tpu top` (docs/observability.md)",
    )
    p.add_argument(
        "--flight_dump", default=None, metavar="PATH",
        help="dump the always-on flight-recorder ring (recent spans/"
        "events/counter deltas, bounded — no trace file needed) "
        "atomically to PATH whenever a request is shed or "
        "quarantined, a dispatch fails, or the service drains "
        "(SIGTERM included), the triggering request's trace id "
        "front and center; render with `pydcop_tpu flight-dump "
        "FILE` (docs/observability.md).  When PATH is a directory, "
        "a per-process file flight-<port|pid>.json is derived "
        "inside it (fleet replicas never clobber each other)",
    )
    p.add_argument(
        "--chaos", default=None, metavar="SPEC",
        help="inject deterministic DEVICE-layer faults into every "
        "dispatch (device_oom=W[:R], device_transient=P[:AFTER], "
        "nan_inject=P[:I]) and WIRE faults into the frame loop "
        "(conn_drop=P[:AFTER], slow_client=W, frame_corrupt=P[:AFTER] "
        "— docs/faults.md): a poisoned or OOM-ing request "
        "degrades/splits under the supervisor while its batchmates "
        "return bit-identical results; dropped/corrupted replies are "
        "replayed from the reply cache on idempotent retry.  The "
        "FLEET kind (replica_kill) is rejected here — it kills "
        "whole replicas, use `pydcop_tpu fleet --chaos`",
    )
    p.add_argument(
        "--chaos_seed", type=int, default=0,
        help="seed for the --chaos fault plan (determinism/replay)",
    )
    add_supervisor_arguments(p)
    add_trace_arguments(p)
    p.set_defaults(func=run_cmd)


def _per_process_path(
    path, prefix: str, port: int
):
    """Resolve a ``--session_checkpoint`` / ``--flight_dump`` target:
    when it is a DIRECTORY (exists as one, or is spelled with a
    trailing separator), derive ``<dir>/<prefix>-<suffix>.json`` with
    a per-process suffix — the bound port when one was requested
    (stable across restarts, so ``--resume`` finds it), else the pid.
    N fleet replicas sharing one config then never clobber each
    other's files."""
    import os

    if path is None:
        return None
    if os.path.isdir(path) or path.endswith(os.sep):
        suffix = str(port) if port else f"pid{os.getpid()}"
        return os.path.join(path, f"{prefix}-{suffix}.json")
    return path


def run_cmd(args) -> int:
    from pydcop_tpu.engine.service import ServiceServer, SolverService
    from pydcop_tpu.telemetry import session

    if args.compile_cache is not None:
        from pydcop_tpu.ops.compile import (
            enable_persistent_compilation_cache,
        )

        enable_persistent_compilation_cache(args.compile_cache)

    session_checkpoint = _per_process_path(
        args.session_checkpoint, "sessions", args.port
    )
    flight_dump = _per_process_path(
        args.flight_dump, "flight", args.port
    )

    stats = None
    with session(args.trace, args.trace_format) as tel:
        try:
            service = SolverService(
                pad_policy=args.pad_policy,
                max_batch=args.max_batch,
                max_wait=args.max_wait,
                instance_bucket=args.instance_bucket,
                chaos=args.chaos,
                chaos_seed=args.chaos_seed,
                retry_budget=args.retry_budget,
                chunk_floor=args.chunk_floor,
                on_numeric_fault=args.on_numeric_fault,
                max_queue=args.max_queue,
                session_memo_bytes=args.session_memo_bytes,
                session_checkpoint=session_checkpoint,
                resume=args.resume,
                flight_dump=flight_dump,
                standbys=args.standbys,
            )
        except ValueError as e:
            # flag/spec usage errors exit cleanly, like the sibling
            # commands — not as a raw traceback (ServiceError IS a
            # RuntimeError, so a bad --resume checkpoint is caught by
            # its own clause below)
            raise SystemExit(f"serve: {e}")
        except RuntimeError as e:
            raise SystemExit(f"serve: {e}")
        server = None
        exporter = None
        prev_term = None
        try:
            server = ServiceServer(
                service, host=args.host, port=args.port,
                max_inflight=args.max_inflight,
            )
            if args.metrics_port is not None:
                from pydcop_tpu.telemetry.export import MetricsExporter

                srv = server

                def _health():
                    return {
                        **service.health(),
                        "inflight": srv.inflight(),
                    }

                exporter = MetricsExporter(
                    tel.metrics.snapshot,
                    _health,
                    host=args.host,
                    port=args.metrics_port,
                )
            import os

            # SIGTERM = "drain and go": the handler only flips the
            # shutdown event; this thread wakes from wait() and runs
            # the same graceful-drain path as a client `shutdown` op
            # or Ctrl-C
            prev_term = signal.signal(
                signal.SIGTERM,
                lambda *_: server.request_shutdown(),
            )
            head = {
                "serving": "%s:%d" % server.address,
                "pid": os.getpid(),
                "sessions_restored": service.stats()[
                    "sessions_restored"
                ],
            }
            if exporter is not None:
                head["metrics"] = "%s:%d" % exporter.address
            if session_checkpoint is not None:
                # the RESOLVED path (a directory target gets its
                # per-process suffix here) — the parent process /
                # test harness reads it back from this line
                head["session_checkpoint"] = session_checkpoint
            if flight_dump is not None:
                head["flight_dump"] = flight_dump
            print(json.dumps(head), flush=True)
            try:
                # the global -t/--timeout doubles as a serve
                # duration bound (handy for scripted benches/tests)
                server.wait(args.timeout)
            except KeyboardInterrupt:
                pass
        finally:
            # EVERY exit path funnels through the graceful drain and
            # emits the final stats line — an interrupted serve must
            # never vanish without flushing its aggregates (and its
            # session checkpoint, when configured).  Order matters
            # twice over: the service drains FIRST, while the wire
            # connections are still open, so queued requests' results
            # actually reach their clients ("finish and deliver") and
            # only then does the server tear the connections down;
            # and the SIGTERM handler stays installed UNTIL the drain
            # finishes, so a re-delivered TERM during it is absorbed
            # instead of killing the process mid-checkpoint.
            try:
                service.close()
            finally:
                try:
                    if server is not None:
                        server.close()
                finally:
                    if exporter is not None:
                        # last out: /healthz keeps answering
                        # "draining" for the whole graceful drain
                        # above, then the scrape endpoint goes away
                        exporter.close()
                    if prev_term is not None:
                        signal.signal(signal.SIGTERM, prev_term)
                    stats = service.stats()
                    print(
                        json.dumps({"stats": stats}, default=str),
                        file=sys.stderr,
                        flush=True,
                    )
    return 0
