"""``pydcop_tpu serve`` — the continuous-batching solver service.

Starts a resident :class:`~pydcop_tpu.engine.service.SolverService`
behind a TCP :class:`~pydcop_tpu.engine.service.ServiceServer`
(newline-JSON frames, ``docs/serving.md``) and serves until a client
sends ``shutdown``, the global ``-t/--timeout`` elapses, or Ctrl-C.

Prints one JSON line ``{"serving": "host:port", "pid": N}`` once the
socket is bound (a parent process can parse it to find an ephemeral
``--port 0``), and a final JSON stats report on exit.
"""

from __future__ import annotations

import json
import sys

from pydcop_tpu.commands._common import (
    add_supervisor_arguments,
    add_trace_arguments,
)


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "serve",
        help="run a resident continuous-batching solver service: an "
        "admission queue coalesces concurrent solve requests into "
        "shape buckets and dispatches merged groups per tick on warm "
        "compiled executables (docs/serving.md)",
    )
    p.add_argument(
        "--host", default="127.0.0.1",
        help="bind address (default 127.0.0.1 — loopback only)",
    )
    p.add_argument(
        "--port", type=int, default=9010,
        help="listen port (0 = ephemeral; the bound port is printed "
        "on the serving line)",
    )
    p.add_argument(
        "--pad_policy", default="pow2", metavar="POLICY",
        help="shape-bucketing policy applied to every request "
        "('pow2' / 'pow2:<floor>' / 'none'): what steers "
        "similarly-sized problems into shared executables and "
        "coalesced dispatches (docs/performance.md); default: pow2",
    )
    p.add_argument(
        "--max_batch", type=int, default=32, metavar="K",
        help="tick policy: dispatch as soon as K requests are "
        "pending (also the per-tick drain cap); default 32",
    )
    p.add_argument(
        "--max_wait", type=float, default=0.01, metavar="SECONDS",
        help="tick policy: never hold the oldest pending request "
        "longer than this before dispatching (the queue-wait bound "
        "behind the service's p99); default 0.01",
    )
    p.add_argument(
        "--instance_bucket", choices=["pow2", "none"], default="pow2",
        help="pad coalesced groups to power-of-two occupancy so the "
        "vmapped runner cache converges on a handful of executables "
        "(steady-state ticks then do ZERO XLA compiles); default pow2",
    )
    p.add_argument(
        "--compile_cache", default=None, metavar="DIR",
        help="persist XLA executables to DIR (jax compilation "
        "cache): a restarted service skips backend compilation of "
        "programs any previous process built (docs/performance.md)",
    )
    p.add_argument(
        "--chaos", default=None, metavar="SPEC",
        help="inject deterministic DEVICE-layer faults into every "
        "dispatch (device_oom=W[:R], device_transient=P[:AFTER], "
        "nan_inject=P[:I] — docs/faults.md): a poisoned or OOM-ing "
        "request degrades/splits under the supervisor while its "
        "batchmates return bit-identical results",
    )
    p.add_argument(
        "--chaos_seed", type=int, default=0,
        help="seed for the --chaos fault plan (determinism/replay)",
    )
    add_supervisor_arguments(p)
    add_trace_arguments(p)
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.engine.service import ServiceServer, SolverService
    from pydcop_tpu.telemetry import session

    if args.compile_cache is not None:
        from pydcop_tpu.ops.compile import (
            enable_persistent_compilation_cache,
        )

        enable_persistent_compilation_cache(args.compile_cache)

    with session(args.trace, args.trace_format):
        service = SolverService(
            pad_policy=args.pad_policy,
            max_batch=args.max_batch,
            max_wait=args.max_wait,
            instance_bucket=args.instance_bucket,
            chaos=args.chaos,
            chaos_seed=args.chaos_seed,
            retry_budget=args.retry_budget,
            chunk_floor=args.chunk_floor,
            on_numeric_fault=args.on_numeric_fault,
        )
        try:
            with ServiceServer(
                service, host=args.host, port=args.port
            ) as server:
                import os

                print(
                    json.dumps(
                        {
                            "serving": "%s:%d" % server.address,
                            "pid": os.getpid(),
                        }
                    ),
                    flush=True,
                )
                try:
                    # the global -t/--timeout doubles as a serve
                    # duration bound (handy for scripted benches/tests)
                    server.wait(args.timeout)
                except KeyboardInterrupt:
                    pass
        finally:
            service.close()
            stats = service.stats()
    print(json.dumps({"stats": stats}, default=str), file=sys.stderr)
    return 0
