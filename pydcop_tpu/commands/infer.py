"""``pydcop_tpu infer`` — exact probabilistic inference over a DCOP's
cost model (the semiring contraction core, ``docs/semirings.md``).

One file prints one result JSON; several files are MANY instances
whose contraction sweeps merge (``api.infer_many`` — same-bucket
contractions share one vmapped dispatch) and print a JSON array.
"""

from __future__ import annotations

from pydcop_tpu.commands._common import (
    add_trace_arguments,
    write_result,
)


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "infer",
        help="exact inference (marginals / log Z / MAP / K-best / "
        "marginal MAP / E[cost]) over the Gibbs distribution "
        "p(x) ~ exp(-beta * cost(x)) via the semiring contraction "
        "engine (ops/semiring.py)",
    )
    p.add_argument(
        "dcop_files", nargs="+",
        help="dcop yaml file(s); several files = several instances "
        "batched into one merged contraction sweep (api.infer_many)",
    )
    p.add_argument(
        "-q", "--query", default="marginals", metavar="QUERY",
        help="marginals: per-variable distributions p(x_v) (+ log_z); "
        "log_z: the log partition function (weighted counting); map: "
        "the exact MAP assignment (max/+, certified like DPOP); "
        "kbest:<k>: the k best assignments in cost order (top-K "
        "cells, exact like map); marginal_map: maximize --map_vars "
        "over the summed weight of the rest (two-block elimination); "
        "expectation: E[cost] under the Gibbs distribution "
        "(+ --external_dists for stochastic externals).  Unknown "
        "names fail with the nearest query suggested",
    )
    p.add_argument(
        "--map_vars", default=None, metavar="V1,V2,...",
        help="marginal_map only: comma-separated names of the "
        "variables maximized over (every other variable is summed "
        "out)",
    )
    p.add_argument(
        "--external_dists", default=None, metavar="JSON",
        help="expectation only: JSON mapping external-variable names "
        "to {value: prob} distributions, e.g. "
        "'{\"sensor\": {\"0\": 0.7, \"1\": 0.3}}' — the named "
        "externals are summed over their distribution instead of "
        "pinned to their current value (values are matched against "
        "the domain, with a string fallback for JSON's string keys)",
    )
    p.add_argument(
        "--order", choices=["pseudo_tree", "min_fill"],
        default="pseudo_tree",
        help="elimination-order heuristic: pseudo_tree (the DPOP DFS "
        "order) or min_fill (greedy width heuristic — often much "
        "narrower on loopy graphs)",
    )
    p.add_argument(
        "--beta", type=float, default=1.0,
        help="inverse temperature of p(x) ~ exp(-beta * cost(x))",
    )
    p.add_argument(
        "--tol", type=float, default=1e-6,
        help="log-domain error budget for device (f32) logsumexp "
        "contractions: a contraction whose accumulated bound would "
        "exceed this runs on host f64 instead (the result reports "
        "its final error_bound); default 1e-6",
    )
    p.add_argument(
        "--device", choices=["auto", "never", "always"],
        default="auto",
        help="device offload of large contractions (auto: tables >= "
        "--device_min_cells cells)",
    )
    p.add_argument(
        "--device_min_cells", type=int, default=1 << 14,
        help="smallest contraction table worth a device dispatch",
    )
    p.add_argument(
        "--pad_policy", default=None, metavar="POLICY",
        help="bucket the contraction dispatches on the pow-2 "
        "level-pack lattice ('pow2' or 'pow2:<floor>') so near-miss "
        "shapes share compiled kernels; default: none for one file, "
        "pow2 for several (docs/performance.md)",
    )
    p.add_argument(
        "--compile_cache", default=None, metavar="DIR",
        help="persist XLA executables to DIR (jax compilation "
        "cache), as in `solve --compile_cache`",
    )
    p.add_argument(
        "--retry_budget", type=int, default=None, metavar="N",
        help="transient device failures retry up to N times per "
        "dispatch (engine/supervisor.py; default 2)",
    )
    p.add_argument(
        "--max_util_bytes", type=int, default=None, metavar="N",
        help="run the sweep memory-bounded (ops/membound.py): every "
        "contraction table stays under N device (f32) bytes by "
        "conditioning a cut set whose assignments ride the "
        "level-pack stack as extra vmapped lanes — exact per the "
        "query's ⊕ contract on instances whose naive tables exceed "
        "device memory; the result carries a 'membound' block "
        "(docs/semirings.md, 'Memory-bounded contraction')",
    )
    p.add_argument(
        "--bnb", choices=["auto", "on", "off"], default="auto",
        help="branch-and-bound pruned contraction kernels: two-pass "
        "⊕-bounded marginalization masks rows a cheap bound proves "
        "irrelevant — map/kbest stay bit-identical, the mass "
        "queries account discarded mass into error_bound.  'auto' "
        "(default) prunes only dispatches whose per-row table "
        "clears a size threshold (docs/semirings.md)",
    )
    p.add_argument(
        "--table_dtype", choices=["f32", "bf16", "int8"], default="f32",
        help="storage precision for packed contraction tables: "
        "'bf16' halves and 'int8' quarters device table bytes while "
        "the accumulator stays f32 — map/kbest stay bit-identical "
        "via the certificate ladder, log_z/marginals carry an "
        "honestly widened error_bound; also shrinks the per-cell "
        "width the --max_util_bytes planner charges "
        "(docs/performance.md, 'Mixed-precision table packs')",
    )
    p.add_argument(
        "--table_format", choices=["dense", "sparse"],
        default="dense",
        help="storage layout for packed contraction tables: "
        "'sparse' COO-packs feasible tuples of hard-constraint-"
        "dominated tables (density <= 0.5) and joins them with "
        "gather/segment-reduce kernels — map/kbest stay "
        "bit-identical to dense, the mass queries fold pack "
        "truncation into error_bound; composes with --table_dtype "
        "and --max_util_bytes (docs/performance.md, 'Sparse "
        "constraint tables')",
    )
    add_trace_arguments(p)
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    import json

    from pydcop_tpu.api import infer, infer_many

    external_dists = None
    if args.external_dists:
        try:
            external_dists = json.loads(args.external_dists)
        except ValueError as e:
            raise SystemExit(
                f"--external_dists is not valid JSON: {e}"
            )
        if not isinstance(external_dists, dict) or not all(
            isinstance(d, dict) for d in external_dists.values()
        ):
            raise SystemExit(
                "--external_dists must be a JSON object mapping "
                "external names to {value: prob} objects"
            )
    kw = dict(
        order=args.order,
        beta=args.beta,
        tol=args.tol,
        device=args.device,
        device_min_cells=args.device_min_cells,
        timeout=args.timeout,
        trace=args.trace,
        trace_format=args.trace_format,
        compile_cache=args.compile_cache,
        retry_budget=args.retry_budget,
        max_util_bytes=args.max_util_bytes,
        bnb=args.bnb,
        table_dtype=args.table_dtype,
        table_format=args.table_format,
        map_vars=(
            [v.strip() for v in args.map_vars.split(",") if v.strip()]
            if args.map_vars
            else None
        ),
        external_dists=external_dists,
    )
    try:
        if len(args.dcop_files) == 1:
            result = infer(
                args.dcop_files[0], args.query,
                pad_policy=args.pad_policy or "none", **kw,
            )
            write_result(args, result)
            return 0
        results = infer_many(
            list(args.dcop_files), args.query,
            pad_policy=args.pad_policy or "pow2", **kw,
        )
    except ValueError as e:
        # bad query / map_vars / dists: the message already carries
        # the nearest-name suggestion — surface it, not a traceback
        raise SystemExit(f"infer: {e}")
    for r in results:
        r.pop("telemetry", None)  # keep the printed JSON compact
    write_result(args, results)
    return 0
