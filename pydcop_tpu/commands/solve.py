"""``pydcop_tpu solve`` (reference: ``pydcop/commands/solve.py``).

One-shot solve of a DCOP yaml; prints the result as JSON:
``{assignment, cost, cycle, msg_count, msg_size, status, time}``.
"""

from __future__ import annotations

import json
import sys

from pydcop_tpu.commands._common import (
    add_collect_arguments,
    add_supervisor_arguments,
    add_trace_arguments,
    parse_algo_params,
    write_metrics,
    write_result,
)


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "solve", help="solve a static DCOP on the batched TPU engine"
    )
    p.add_argument("dcop_files", nargs="+", help="dcop yaml file(s)")
    p.add_argument("-a", "--algo", required=True, help="algorithm name")
    p.add_argument(
        "-p", "--algo_params", action="append", default=[],
        metavar="NAME:VALUE", help="algorithm parameter (repeatable)",
    )
    p.add_argument(
        "-d", "--distribution", default=None,
        help="distribution strategy name or `distribute --output` "
        "yaml file: shapes the host modes' placement (thread agent "
        "grouping, process-per-agent, island subgraphs); the batched "
        "engine solves regardless of placement",
    )
    p.add_argument(
        "-m", "--mode", choices=["thread", "sim", "process", "tpu"],
        default="tpu",
        help="execution mode: tpu = batched engine (default); thread = "
        "host thread-per-agent runtime; sim = deterministic async "
        "event loop; process = one local OS process per agent over the "
        "TCP host runtime (the reference's run_local_process_dcop)",
    )
    p.add_argument(
        "--nb_agents", type=int, default=None,
        help="process count for --mode process (default: one per "
        "declared agent, capped at the CPU count)",
    )
    p.add_argument(
        "--accel_agents", nargs="+", default=None, metavar="NAME",
        help="(thread/sim/process modes) agents whose placed subgraph "
        "runs as ONE compiled array-engine island instead of "
        "per-computation host code (the heterogeneous strong-host "
        "deployment; maxsum/amaxsum and the dsa family)",
    )
    p.add_argument(
        "--msg_log", default=None, metavar="FILE",
        help="(thread/sim/process modes) dump every delivered "
        "message's full content to FILE as JSON lines (the reference "
        "Messaging's per-message log; process mode writes "
        "FILE.<agent> per agent)",
    )
    p.add_argument("--rounds", type=int, default=200, help="round budget")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--convergence_chunks", type=int, default=0,
        help="stop after N unchanged chunks (0 = run all rounds)",
    )
    p.add_argument(
        "--checkpoint", default=None,
        help="write the run state to this .npz file as the run proceeds",
    )
    p.add_argument(
        "--checkpoint_every", type=int, default=1,
        help="chunks between checkpoint writes",
    )
    p.add_argument(
        "--resume", action="store_true",
        help="restore --checkpoint (if present) and continue the run",
    )
    p.add_argument(
        "--uiport", type=int, default=None,
        help="serve a live observability feed on this port while "
        "solving (SSE /events + /state + built-in page, see "
        "infrastructure/ui.py)",
    )
    p.add_argument(
        "--profile", default=None, metavar="DIR",
        help="capture a jax.profiler trace of the solve into DIR "
        "(inspect with tensorboard or xprof)",
    )
    p.add_argument(
        "--chaos", default=None, metavar="SPEC",
        help="inject deterministic faults (spec format: "
        "docs/faults.md; same --chaos_seed => identical fault "
        "sequence).  thread/process modes: message-plane kinds — "
        "drop/dup/reorder/delay probabilities, timed partitions, "
        "crash schedules.  tpu mode (incl. --many): device-layer "
        "kinds — device_oom=W[:R], device_transient=P[:AFTER], "
        "nan_inject=P[:I] — injected at the supervised device-"
        "dispatch seam (engine/supervisor.py)",
    )
    p.add_argument(
        "--chaos_seed", type=int, default=0,
        help="seed for the --chaos fault plan (determinism/replay)",
    )
    p.add_argument(
        "--restarts", type=int, default=1,
        help="run this many independent solver instances batched in "
        "one device program (vmap) and report the best — parallel "
        "restarts for stochastic algorithms",
    )
    p.add_argument(
        "--pad_policy", default="none", metavar="POLICY",
        help="bucket the compiled problem's array shapes ('pow2' or "
        "'pow2:<floor>') so similarly-sized problems reuse jitted "
        "executables instead of recompiling; for dpop it buckets the "
        "UTIL level dispatches instead (level-pack keys — results "
        "bit-identical, docs/performance.md); default: none",
    )
    p.add_argument(
        "--many", action="store_true",
        help="treat each DCOP FILE as a SEPARATE problem instance and "
        "solve them together (api.solve_many): same-shaped instances "
        "batch into one vmapped device program — or, for dpop, one "
        "merged level-synchronous UTIL sweep — pass --pad_policy "
        "pow2 so similarly-sized files land in the same shape bucket "
        "(docs/performance.md, 'Cross-instance batching').  Prints a "
        "JSON array of per-instance results.  Batched-engine (tpu) "
        "mode only",
    )
    p.add_argument(
        "--compile_cache", default=None, metavar="DIR",
        help="persist XLA executables to DIR (jax compilation cache): "
        "repeated runs of the same program skip backend compilation "
        "entirely, across processes (docs/performance.md)",
    )
    p.add_argument(
        "--max_util_bytes", type=int, default=None, metavar="N",
        help="(exact algorithms with a bounded-memory plan — dpop) "
        "cap every UTIL/message table at N device (f32) bytes: the "
        "memory-bounded contraction planner (ops/membound.py) "
        "conditions a cut set whose assignments ride the level-pack "
        "stack as extra vmapped lanes — exact results on instances "
        "whose naive tables exceed device memory, a device OOM "
        "re-plans at half budget, and the result carries a "
        "'membound' block (docs/semirings.md)",
    )
    p.add_argument(
        "--bnb", choices=["auto", "on", "off"], default=None,
        help="branch-and-bound pruned contraction kernels "
        "(algorithms with a device contraction phase — dpop, "
        "maxsum): two-pass ⊕-bounded marginalization skips rows a "
        "cheap bound proves irrelevant against a greedy incumbent — "
        "results bit-identical, dead certification/re-evaluation "
        "work skipped.  'auto' (default) prunes only dispatches "
        "whose per-row table clears a size threshold "
        "(docs/semirings.md, 'Branch-and-bound pruning')",
    )
    p.add_argument(
        "--table_dtype", choices=["f32", "bf16", "int8"], default=None,
        help="storage precision for packed contraction tables "
        "(algorithms with a device contraction phase — dpop): "
        "'bf16' halves and 'int8' quarters the bytes each table "
        "ships to the device while the accumulator stays f32 and "
        "the certificate ladder repairs uncertain nodes back to "
        "f32/f64 — min/max-sum results stay bit-identical to the "
        "f32 path.  Also shrinks the per-cell width the "
        "--max_util_bytes planner charges "
        "(docs/performance.md, 'Mixed-precision table packs')",
    )
    p.add_argument(
        "--table_format", choices=["dense", "sparse"], default=None,
        help="storage layout for packed contraction tables "
        "(algorithms with a device contraction phase — dpop): "
        "'sparse' COO-packs feasible tuples of hard-constraint-"
        "dominated tables and joins them with gather/segment-reduce "
        "kernels — min/max-sum results stay bit-identical to dense "
        "and a >=90%%-infeasible workload ships a fraction of the "
        "dense bytes (docs/performance.md, 'Sparse constraint "
        "tables')",
    )
    add_supervisor_arguments(p)
    add_collect_arguments(p)
    add_trace_arguments(p)
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.api import solve

    params = parse_algo_params(args.algo_params)
    if args.bnb is not None:
        # an algo param (dpop/maxsum declare it) — the flag is just
        # the discoverable spelling, like --max_util_bytes
        params = {**params, "bnb": args.bnb}
    if args.table_dtype is not None:
        params = {**params, "table_dtype": args.table_dtype}
    if args.table_format is not None:
        params = {**params, "table_format": args.table_format}
    if args.many:
        return _run_many_cmd(args, params)
    profile_ctx = None
    if args.profile:
        import jax

        profile_ctx = jax.profiler.trace(args.profile)
        profile_ctx.__enter__()
    try:
        result = solve(
            args.dcop_files
            if len(args.dcop_files) > 1
            else args.dcop_files[0],
            args.algo,
            params,
            rounds=args.rounds,
            timeout=args.timeout,
            seed=args.seed,
            convergence_chunks=args.convergence_chunks,
            checkpoint_path=args.checkpoint,
            checkpoint_every=args.checkpoint_every,
            resume=args.resume,
            mode="batched" if args.mode == "tpu" else args.mode,
            ui_port=args.uiport,
            n_restarts=args.restarts,
            nb_agents=args.nb_agents,
            msg_log=args.msg_log,
            accel_agents=args.accel_agents,
            distribution=args.distribution,
            chaos=args.chaos,
            chaos_seed=args.chaos_seed,
            trace=args.trace,
            trace_format=args.trace_format,
            pad_policy=args.pad_policy,
            compile_cache=args.compile_cache,
            retry_budget=args.retry_budget,
            chunk_floor=args.chunk_floor,
            on_numeric_fault=args.on_numeric_fault,
            max_util_bytes=args.max_util_bytes,
        )
    finally:
        # flush the trace even when the solve raises — a profile of a
        # failing run is exactly when you want the data
        if profile_ctx is not None:
            profile_ctx.__exit__(None, None, None)
    write_metrics(args, result)
    result.pop("cost_trace", None)  # keep the printed JSON compact
    result.pop("trace_subsampled", None)
    result.pop("trace_msgs", None)
    write_result(args, result)
    return 0


def _run_many_cmd(args, params) -> int:
    """``solve --many``: each file is one instance, solved through
    :func:`pydcop_tpu.api.solve_many` (cross-instance batching)."""
    from pydcop_tpu.api import solve_many

    if args.max_util_bytes is not None:
        # solve_many takes it through the per-algorithm params (the
        # budget is a dpop algo param — docs/semirings.md)
        params = {**params, "max_util_bytes": args.max_util_bytes}
    if args.mode != "tpu":
        raise SystemExit(
            "--many batches instances on the batched engine; "
            f"--mode {args.mode} does not apply"
        )
    for flag, name in (
        (args.checkpoint, "--checkpoint"),
        (args.resume, "--resume"),
        (args.uiport, "--uiport"),
        (args.msg_log, "--msg_log"),
        (args.accel_agents, "--accel_agents"),
        (args.distribution, "--distribution"),
        (args.nb_agents, "--nb_agents"),
        (args.profile, "--profile"),
    ):
        if flag:
            raise SystemExit(
                f"{name} is a single-run option; it does not compose "
                "with --many (solve the instances individually for it)"
            )
    results = solve_many(
        list(args.dcop_files),
        args.algo,
        params,
        rounds=args.rounds,
        timeout=args.timeout,
        seed=args.seed,
        convergence_chunks=args.convergence_chunks,
        n_restarts=args.restarts,
        pad_policy=args.pad_policy,
        trace=args.trace,
        trace_format=args.trace_format,
        compile_cache=args.compile_cache,
        chaos=args.chaos,
        chaos_seed=args.chaos_seed,
        retry_budget=args.retry_budget,
        chunk_floor=args.chunk_floor,
        on_numeric_fault=args.on_numeric_fault,
    )
    for r in results:
        r.pop("cost_trace", None)  # keep the printed JSON compact
        r.pop("telemetry", None)
    write_result(args, results)
    return 0
