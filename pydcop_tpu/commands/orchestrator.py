"""``pydcop_tpu orchestrator`` (reference: ``pydcop/commands/orchestrator.py``).

Start the management plane for a cross-process run: wait for
``--nb_agents`` agent processes to register on ``--port``, deploy the
problem + algorithm to them, run the sharded SPMD solve as process 0 of
the ``jax.distributed`` cluster, cross-check every agent's replicated
result, and print the assembled JSON (same shape as ``solve``).

Example (two terminals)::

    pydcop_tpu orchestrator coloring.yaml -a maxsum --port 9500
    pydcop_tpu agent --names a1 --orchestrator localhost:9500
"""

from __future__ import annotations

from pydcop_tpu.commands._common import (
    add_collect_arguments,
    add_trace_arguments,
    parse_algo_params,
    write_metrics,
    write_result,
)
from pydcop_tpu.telemetry import session as telemetry_session


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "orchestrator",
        help="serve a cross-process run: deploy to agents, solve as "
        "process 0, assemble the result",
    )
    p.add_argument("dcop_files", nargs="+", help="dcop yaml file(s)")
    p.add_argument("-a", "--algo", required=True, help="algorithm name")
    p.add_argument(
        "-p", "--algo_params", action="append", default=[],
        metavar="NAME:VALUE", help="algorithm parameter (repeatable)",
    )
    p.add_argument("--port", type=int, default=9500)
    p.add_argument(
        "--nb_agents", type=int, default=1,
        help="agent processes to wait for before starting",
    )
    p.add_argument(
        "--advertise_host", default="localhost",
        help="hostname agents should use to reach the jax.distributed "
        "coordinator (multi-host runs: this machine's address)",
    )
    p.add_argument("--rounds", type=int, default=200)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--chunk_size", type=int, default=64)
    p.add_argument(
        "--scenario", default=None, metavar="FILE",
        help="scenario yaml to replay across all processes (dynamic "
        "run: remove/add agents, set external values)",
    )
    p.add_argument(
        "--ktarget", type=int, default=0,
        help="replication level for scenario runs (k-resilience)",
    )
    p.add_argument(
        "--heartbeat_timeout", type=float, default=120.0,
        help="seconds an agent may miss the chunk barrier before the "
        "run is failed",
    )
    p.add_argument(
        "--first_barrier_min", type=float, default=None,
        help="--elastic: minimum budget (seconds) for the FIRST chunk "
        "barrier of an epoch, which also covers jax import + cold XLA "
        "compile on every worker (default 600, or the "
        "PYDCOP_TPU_ELASTIC_FIRST_BARRIER_MIN env var)",
    )
    p.add_argument(
        "--abort_grace", type=float, default=5.0,
        help="seconds to wait for a clean unwind after a peer death "
        "before force-exiting a wedged process",
    )
    p.add_argument(
        "--uiport", type=int, default=None,
        help="serve a live observability feed on this port during "
        "the run (SSE /events + /state, see infrastructure/ui.py)",
    )
    p.add_argument(
        "--elastic", action="store_true",
        help="resilient runtime: survive agent death mid-solve by "
        "re-forming the cluster on the survivors (dead agents' "
        "variables migrate to replicas with --ktarget, else freeze "
        "at their last value) — see infrastructure/elastic.py",
    )
    p.add_argument(
        "--register_timeout", type=float, default=120.0,
        help="seconds to wait for all --nb_agents registrations",
    )
    p.add_argument(
        "-d", "--distribution", default=None,
        help="--runtime host placement: a distribution strategy name "
        "(oneagent/adhoc/heur_comhost/...) computed over the "
        "registered agents, or a yaml file with a `distribution:` "
        "mapping (the `distribute --output` format); default "
        "round-robin",
    )
    p.add_argument(
        "--accel_agents", nargs="+", default=None, metavar="AGENT",
        help="--runtime host only: agent name(s) whose placed "
        "computations run as ONE compiled array-engine island (TPU "
        "when the agent's machine has one) behind per-node proxies — "
        "the heterogeneous strong-host deployment.  Requires island "
        "support in the algorithm (maxsum/amaxsum and the dsa family)",
    )
    p.add_argument(
        "--chaos", default=None, metavar="SPEC",
        help="--runtime host only: ship a deterministic fault-"
        "injection plan to every agent's message plane (drop/dup/"
        "reorder/delay probabilities, timed partitions, crash "
        "schedules; spec format: docs/faults.md)",
    )
    p.add_argument(
        "--chaos_seed", type=int, default=0,
        help="seed for the --chaos fault plan (same seed => identical "
        "fault sequence, recorded in the result for replay)",
    )
    p.add_argument(
        "--grace_period", type=float, default=5.0,
        help="--runtime host: transient-fault grace window (seconds) "
        "— failed sends are retried with backoff for this long before "
        "a link is declared dead; a permanent message-plane failure "
        "then degrades the run to the anytime-best result "
        "(status=degraded) instead of failing it",
    )
    p.add_argument(
        "--runtime", choices=["spmd", "host"], default="spmd",
        help="spmd (default): batched engine over a jax.distributed "
        "mesh, every process computes the whole sharded problem in "
        "lockstep.  host: message-driven agents over TCP — each agent "
        "runs only its placed computations, exchanging simple_repr "
        "JSON messages (the reference's heterogeneous deployment; "
        "agents need no accelerator)",
    )
    add_collect_arguments(p)
    add_trace_arguments(p)
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.dcop.yamldcop import dcop_yaml as dump_yaml
    from pydcop_tpu.dcop.yamldcop import load_dcop_from_file
    from pydcop_tpu.infrastructure.orchestrator import run_orchestrator

    # pure argument validation FIRST — before any problem parsing
    if args.distribution and args.runtime != "host":
        raise SystemExit(
            "orchestrator: --distribution applies to --runtime host "
            "(the SPMD runtime shards the whole compiled problem; "
            "placement is the mesh layout)"
        )
    if args.accel_agents and args.runtime != "host":
        raise SystemExit(
            "orchestrator: --accel_agents applies to --runtime host "
            "(the SPMD runtime is all-accelerator already)"
        )
    if args.chaos and args.runtime != "host":
        raise SystemExit(
            "orchestrator: --chaos applies to --runtime host (the "
            "SPMD runtime has no per-agent message plane; use "
            "--elastic + real kills, or `run --chaos` for scripted "
            "crashes on the batched engine)"
        )
    if args.chaos:
        from pydcop_tpu.faults import FaultPlan, FaultSpecError

        try:
            plan = FaultPlan.from_spec(args.chaos, args.chaos_seed)
        except FaultSpecError as e:
            raise SystemExit(f"orchestrator: {e}")
        if plan.wire_faults_configured:
            # a silently-inert clause would record the spec as
            # applied while injecting nothing
            raise SystemExit(
                "orchestrator: wire-level chaos kinds (conn_drop/"
                "slow_client/frame_corrupt) inject at the solver "
                "service's frame loop — use `pydcop_tpu serve "
                "--chaos` (docs/serving.md)"
            )
        if plan.device_faults_configured:
            # same inert-clause rule for the device layer: the host
            # orchestrator runtime has no supervised device dispatch
            raise SystemExit(
                "orchestrator: device-layer chaos kinds (device_oom/"
                "device_oom_bytes/device_transient/nan_inject) "
                "inject at the batched engine's supervised dispatch "
                "— use `solve`/`run --chaos` (docs/faults.md)"
            )
        if plan.fleet_faults_configured:
            raise SystemExit(
                "orchestrator: fleet-level chaos kinds "
                "(replica_kill) act on a replicated serving fleet's "
                "processes — use `pydcop_tpu fleet --chaos` "
                "(docs/faults.md)"
            )
    placement = None
    dist_name = None
    if args.distribution:
        import os

        if os.path.exists(args.distribution):
            import yaml

            with open(args.distribution) as f:
                spec = yaml.safe_load(f)
            if (
                not isinstance(spec, dict)
                or "distribution" not in spec
                or not isinstance(spec["distribution"], dict)
            ):
                raise SystemExit(
                    f"orchestrator: {args.distribution} is not a "
                    "placement file (expected a yaml `distribution:` "
                    "mapping of agent -> computation names, the "
                    "`distribute --output` format)"
                )
            placement = spec["distribution"]
            bad = {
                a: v
                for a, v in placement.items()
                if not isinstance(v, list)
                or not all(isinstance(c, str) for c in v)
            }
            if bad:
                raise SystemExit(
                    "orchestrator: placement entries must be lists of "
                    f"computation names; got {bad}"
                )
        else:
            from pydcop_tpu.distribution import (
                load_distribution_module,
            )

            try:  # fail fast on a typo'd name, not after registration
                load_distribution_module(args.distribution)
            except Exception as e:
                raise SystemExit(f"orchestrator: {e}")
            dist_name = args.distribution

    # load (merging multi-file specs); the SPMD runtimes re-dump ONE
    # self-contained yaml text for their deploy messages below — the
    # host runtime serializes internally
    dcop = load_dcop_from_file(
        args.dcop_files if len(args.dcop_files) > 1 else args.dcop_files[0]
    )

    scenario_yaml = None
    if args.scenario:
        with open(args.scenario) as f:
            scenario_yaml = f.read()

    if args.runtime == "host":
        from pydcop_tpu.infrastructure.hostnet import (
            PlacementError,
            run_host_orchestrator,
        )

        if args.elastic or args.scenario:
            raise SystemExit(
                "orchestrator: --runtime host does not support "
                "--elastic/--scenario (the SPMD runtime carries the "
                "scripted-dynamics modes); --ktarget IS supported: "
                "replica-based migration on agent death"
            )
        # algo/params usage errors fail fast and cleanly, before any
        # agent registration
        from pydcop_tpu.algorithms import (
            load_algorithm_module,
            prepare_algo_params,
            require_island_support,
        )

        try:
            _mod = load_algorithm_module(args.algo)
            prepare_algo_params(
                parse_algo_params(args.algo_params), _mod.algo_params
            )
            if not hasattr(_mod, "build_computation"):
                raise ValueError(
                    f"{args.algo} has no host (message-driven) "
                    "implementation — use the SPMD runtime for "
                    "batched-only algorithms"
                )
            if args.accel_agents:
                require_island_support(_mod, args.algo)
        except ValueError as e:
            raise SystemExit(f"orchestrator: {e}")
        try:
            with telemetry_session(args.trace, args.trace_format) as tel:
                result = run_host_orchestrator(
                    dcop,
                    args.algo,
                    parse_algo_params(args.algo_params),
                    nb_agents=args.nb_agents,
                    port=args.port,
                    rounds=args.rounds,
                    timeout=args.timeout,
                    seed=args.seed,
                    register_timeout=args.register_timeout,
                    distribution=dist_name,
                    placement=placement,
                    ui_port=args.uiport,
                    accel_agents=args.accel_agents,
                    k_target=args.ktarget or 0,
                    chaos=args.chaos,
                    chaos_seed=args.chaos_seed,
                    grace_period=args.grace_period,
                )
                result["telemetry"] = tel.summary()
        except PlacementError as e:  # usage errors: clean exit
            raise SystemExit(f"orchestrator: {e}")
        write_metrics(args, result)
        result.pop("cost_trace", None)  # keep the printed JSON compact
        result.pop("trace_subsampled", None)
        result.pop("trace_msgs", None)
        write_result(args, result)
        return 0

    dcop_yaml = dump_yaml(dcop)

    if args.elastic:
        from pydcop_tpu.infrastructure.elastic import (
            run_elastic_orchestrator,
        )

        if args.scenario:
            raise SystemExit(
                "orchestrator: --elastic and --scenario are separate "
                "dynamics modes (reactive vs scripted); use one"
            )
        with telemetry_session(args.trace, args.trace_format) as tel:
            result = run_elastic_orchestrator(
                dcop_yaml,
                args.algo,
                parse_algo_params(args.algo_params),
                port=args.port,
                nb_agents=args.nb_agents,
                rounds=args.rounds,
                seed=args.seed,
                chunk_size=args.chunk_size,
                timeout=args.timeout,
                advertise_host=args.advertise_host,
                heartbeat_timeout=args.heartbeat_timeout,
                k_target=args.ktarget,
                ui_port=args.uiport,
                abort_grace=args.abort_grace,
                first_barrier_min=args.first_barrier_min,
            )
            result["telemetry"] = tel.summary()
        write_result(args, result)
        return 0

    with telemetry_session(args.trace, args.trace_format) as tel:
        result = run_orchestrator(
            dcop_yaml,
            args.algo,
            parse_algo_params(args.algo_params),
            port=args.port,
            nb_agents=args.nb_agents,
            rounds=args.rounds,
            seed=args.seed,
            chunk_size=args.chunk_size,
            timeout=args.timeout,
            advertise_host=args.advertise_host,
            heartbeat_timeout=args.heartbeat_timeout,
            abort_grace=args.abort_grace,
            scenario_yaml=scenario_yaml,
            k_target=args.ktarget,
            ui_port=args.uiport,
        )
        result["telemetry"] = tel.summary()
    write_metrics(args, result)
    result.pop("cost_trace", None)  # keep the printed JSON compact
    result.pop("trace_subsampled", None)
    result.pop("trace_msgs", None)
    write_result(args, result)
    return 0
