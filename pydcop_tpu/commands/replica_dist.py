"""``pydcop_tpu replica_dist`` — placeholder, implemented in a later milestone
(reference: ``pydcop/commands/replica_dist.py``)."""


def set_parser(subparsers) -> None:
    p = subparsers.add_parser("replica_dist", help="(not yet implemented)")
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    raise SystemExit("replica_dist: not yet implemented in this build")
