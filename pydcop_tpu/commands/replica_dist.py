"""``pydcop_tpu replica_dist`` (reference: ``pydcop/commands/replica_dist.py``).

Compute a k-resilient replica placement offline: place the computations
with a distribution strategy, then place k replicas of each via
uniform-cost search over hosting + route costs.
"""

from __future__ import annotations


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "replica_dist", help="compute k-resilient replica placement"
    )
    p.add_argument("dcop_files", nargs="+", help="dcop yaml file(s)")
    p.add_argument("-k", "--ktarget", type=int, required=True)
    p.add_argument(
        "-a", "--algo", required=True,
        help="algorithm (graph model + footprints)",
    )
    p.add_argument(
        "-d", "--distribution", default="oneagent",
        help="distribution strategy or distribution yaml for the "
        "primary placement",
    )
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    import os

    import yaml

    from pydcop_tpu.algorithms import load_algorithm_module
    from pydcop_tpu.commands._common import write_result
    from pydcop_tpu.dcop.yamldcop import load_dcop_from_file
    from pydcop_tpu.distribution.objects import Distribution
    from pydcop_tpu.graphs import load_graph_module
    from pydcop_tpu.replication import replica_distribution

    module = load_algorithm_module(args.algo)
    dcop = load_dcop_from_file(
        args.dcop_files if len(args.dcop_files) > 1 else args.dcop_files[0]
    )
    graph = load_graph_module(module.GRAPH_TYPE).build_computation_graph(dcop)
    computation_memory = getattr(module, "computation_memory", None)
    nodes = {n.name: n for n in graph.nodes}

    if os.path.isfile(args.distribution):
        with open(args.distribution) as f:
            dist = Distribution(yaml.safe_load(f)["distribution"])
    else:
        from pydcop_tpu.distribution import compute_distribution

        dist = compute_distribution(
            args.distribution,
            graph,
            dcop.agents.values(),
            hints=dcop.dist_hints,
            algo_module=module,
            computation_memory=computation_memory,
        )

    def footprint(comp: str) -> float:
        if computation_memory is None or comp not in nodes:
            return 1.0
        return float(computation_memory(nodes[comp]))

    replicas = replica_distribution(
        dist, dcop.agents.values(), args.ktarget, footprint=footprint
    )
    write_result(
        args,
        {
            "distribution": dist.mapping,
            "replica_distribution": replicas.mapping,
            "ktarget": args.ktarget,
        },
    )
    return 0
