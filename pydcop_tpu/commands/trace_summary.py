"""``pydcop_tpu trace-summary`` — aggregate a telemetry trace.

Reads a ``--trace`` file (JSONL or Chrome ``trace_event`` format,
auto-detected) and prints per-phase span totals, event counts,
injected-fault counts, per-agent activity, and the embedded metrics
snapshot.  See ``docs/observability.md``.
"""

from __future__ import annotations

import json


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "trace-summary",
        help="aggregate a --trace telemetry file (per-phase / "
        "per-agent totals); --requests stitches several files into "
        "per-request timelines by wire-propagated trace id",
    )
    p.add_argument(
        "trace_file", nargs="+",
        help="trace file(s) (jsonl or chrome); the default summary "
        "reads the first, --requests correlates ALL of them (e.g. a "
        "client-side trace plus the server's)",
    )
    p.add_argument(
        "--requests", action="store_true", dest="as_requests",
        help="stitch one correlated timeline per request across the "
        "given trace files: client attempt spans and server "
        "queue/dispatch/phase spans joined on the trace id the wire "
        "protocol propagates (docs/observability.md, 'Serving "
        "observability')",
    )
    p.add_argument(
        "--json", action="store_true", dest="as_json",
        help="print the aggregates as JSON instead of a table",
    )
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.telemetry.summary import (
        format_requests,
        format_summary,
        load_trace,
        stitch_requests,
        summarize,
    )

    try:
        tracesets = [load_trace(p) for p in args.trace_file]
    except (OSError, ValueError) as e:
        raise SystemExit(f"trace-summary: {e}")
    if args.as_requests:
        stitched = stitch_requests(tracesets)
        out = (
            json.dumps(stitched, indent=2, default=str)
            if args.as_json
            else format_requests(stitched)
        )
    else:
        if len(tracesets) > 1:
            raise SystemExit(
                "trace-summary: several trace files only combine "
                "under --requests (the aggregate summary is "
                "per-process — run it per file)"
            )
        s = summarize(tracesets[0])
        out = (
            json.dumps(s, indent=2, default=str)
            if args.as_json
            else format_summary(s)
        )
    print(out)
    if getattr(args, "output", None):
        with open(args.output, "w") as f:
            f.write(out + "\n")
    return 0
