"""``pydcop_tpu trace-summary`` — aggregate a telemetry trace.

Reads a ``--trace`` file (JSONL or Chrome ``trace_event`` format,
auto-detected) and prints per-phase span totals, event counts,
injected-fault counts, per-agent activity, and the embedded metrics
snapshot.  See ``docs/observability.md``.
"""

from __future__ import annotations

import json


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "trace-summary",
        help="aggregate a --trace telemetry file (per-phase / "
        "per-agent totals)",
    )
    p.add_argument("trace_file", help="trace file (jsonl or chrome)")
    p.add_argument(
        "--json", action="store_true", dest="as_json",
        help="print the aggregates as JSON instead of a table",
    )
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.telemetry.summary import (
        format_summary,
        load_trace,
        summarize,
    )

    try:
        records = load_trace(args.trace_file)
    except (OSError, ValueError) as e:
        raise SystemExit(f"trace-summary: {e}")
    s = summarize(records)
    out = (
        json.dumps(s, indent=2, default=str)
        if args.as_json
        else format_summary(s)
    )
    print(out)
    if getattr(args, "output", None):
        with open(args.output, "w") as f:
            f.write(out + "\n")
    return 0
