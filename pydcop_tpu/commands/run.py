"""``pydcop_tpu run`` — placeholder, implemented in a later milestone
(reference: ``pydcop/commands/run.py``)."""


def set_parser(subparsers) -> None:
    p = subparsers.add_parser("run", help="(not yet implemented)")
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    raise SystemExit("run: not yet implemented in this build")
