"""``pydcop_tpu run`` (reference: ``pydcop/commands/run.py``).

Solve a DCOP while playing a scenario of dynamic events (agent
departures/arrivals, external-variable changes), with optional
k-resilient replication + repair.  Prints the result JSON including the
event log.
"""

from __future__ import annotations

from pydcop_tpu.commands._common import (
    add_collect_arguments,
    add_supervisor_arguments,
    add_trace_arguments,
    parse_algo_params,
    write_metrics,
    write_result,
)


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "run", help="solve a DCOP while playing a dynamic scenario"
    )
    p.add_argument("dcop_files", nargs="+", help="dcop yaml file(s)")
    p.add_argument("-a", "--algo", required=True, help="algorithm name")
    p.add_argument(
        "-p", "--algo_params", action="append", default=[],
        metavar="NAME:VALUE", help="algorithm parameter (repeatable)",
    )
    p.add_argument(
        "-s", "--scenario", default=None,
        help="scenario yaml file (or use --chaos crash schedules)",
    )
    p.add_argument(
        "--chaos", default=None, metavar="SPEC",
        help="inject deterministic faults (spec format: "
        "docs/faults.md): crash=AGENT@T clauses generate the "
        "scenario — each becomes a deterministic remove_agent event "
        "at T seconds — and the device-layer kinds (device_oom, "
        "device_transient, nan_inject) inject at the supervised "
        "device-dispatch seam of every segment "
        "(engine/supervisor.py); message-plane fault clauses are "
        "rejected here (the batched engine has no message plane).  "
        "Device-only specs compose with -s/--scenario",
    )
    p.add_argument(
        "--chaos_seed", type=int, default=0,
        help="seed recorded with the --chaos plan (crash schedules "
        "are explicit, so this only tags the replay record)",
    )
    p.add_argument(
        "-d", "--distribution", default="oneagent",
        help="distribution strategy for the initial placement",
    )
    p.add_argument(
        "-k", "--ktarget", type=int, default=1,
        help="replicas per computation (0 disables replication)",
    )
    p.add_argument(
        "--rounds_per_second", type=float, default=20.0,
        help="scenario delay seconds → engine rounds scale",
    )
    p.add_argument(
        "--final_rounds", type=int, default=100,
        help="rounds before the first and after the last event",
    )
    p.add_argument(
        "--repair_algo", default="mgm",
        help="algorithm solving the reparation DCOP",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--pad_policy", default="none", metavar="POLICY",
        help="bucket each segment's compiled array shapes ('pow2' or "
        "'pow2:<floor>'): segments whose size changed within a bucket "
        "(e.g. one lost variable) reuse the previous segment's "
        "compiled executables instead of paying an XLA compile "
        "(docs/performance.md); default: none",
    )
    p.add_argument(
        "--compile_cache", default=None, metavar="DIR",
        help="persist XLA executables to DIR (jax compilation cache): "
        "repeated runs skip backend compilation across processes "
        "(docs/performance.md)",
    )
    add_supervisor_arguments(p)
    add_collect_arguments(p)
    add_trace_arguments(p)
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.dcop.yamldcop import (
        load_dcop_from_file,
        load_scenario_from_file,
    )
    from pydcop_tpu.distribution import ImpossibleDistributionException
    from pydcop_tpu.engine.dynamic import run_dynamic

    dcop = load_dcop_from_file(
        args.dcop_files if len(args.dcop_files) > 1 else args.dcop_files[0]
    )
    chaos_plan = None
    if args.chaos:
        from pydcop_tpu.dcop.scenario import (
            EventAction,
            Scenario,
            ScenarioEvent,
        )
        from pydcop_tpu.faults import FaultPlan, FaultSpecError

        try:
            chaos_plan = FaultPlan.from_spec(args.chaos, args.chaos_seed)
        except FaultSpecError as e:
            raise SystemExit(f"run: {e}")
        if chaos_plan.message_faults_configured:
            raise SystemExit(
                "run: the batched dynamic engine has no message plane "
                "— only crash=AGENT@T clauses and the device-layer "
                "kinds (device_oom/device_transient/nan_inject) apply "
                "here; message-plane faults (drop/dup/reorder/delay/"
                "partition) need the host runtimes (solve --mode "
                "thread/process, orchestrator --runtime host)"
            )
        if chaos_plan.wire_faults_configured:
            raise SystemExit(
                "run: wire-level chaos kinds (conn_drop/slow_client/"
                "frame_corrupt) inject at the solver service's frame "
                "loop — use `pydcop_tpu serve --chaos` "
                "(docs/serving.md)"
            )
        if chaos_plan.fleet_faults_configured:
            raise SystemExit(
                "run: fleet-level chaos kinds (replica_kill) act on "
                "a replicated serving fleet's processes — use "
                "`pydcop_tpu fleet --chaos` (docs/faults.md)"
            )
        if not chaos_plan.crashes and not chaos_plan.device_faults_configured:
            raise SystemExit(
                "run: --chaos without crash=AGENT@T or device-layer "
                "clauses schedules nothing for the batched engine"
            )
        if chaos_plan.crashes and args.scenario:
            raise SystemExit(
                "run: --scenario and --chaos crash schedules are two "
                "sources of scripted dynamics; use one (device-only "
                "--chaos specs DO compose with --scenario)"
            )
        unknown = set(chaos_plan.crashes) - set(dcop.agents)
        if unknown:
            raise SystemExit(
                f"run: --chaos crashes unknown agent(s) "
                f"{sorted(unknown)} (declared: {sorted(dcop.agents)})"
            )
        # crash schedules → deterministic remove_agent events, in
        # (time, name) order so equal-time crashes replay identically
        events = []
        t_prev = 0.0
        for name, t in sorted(
            chaos_plan.crashes.items(), key=lambda kv: (kv[1], kv[0])
        ):
            if t > t_prev:
                events.append(ScenarioEvent(delay=t - t_prev))
                t_prev = t
            events.append(
                ScenarioEvent(
                    id=f"chaos_crash_{name}",
                    actions=[EventAction("remove_agent", agent=name)],
                )
            )
        if chaos_plan.crashes:
            scenario = Scenario(events)
        elif args.scenario:
            scenario = load_scenario_from_file(args.scenario)
        else:
            raise SystemExit(
                "run: a dynamics source is required — -s/--scenario "
                "FILE or --chaos 'crash=AGENT@T,...' (a device-only "
                "--chaos spec injects faults but scripts no dynamics)"
            )
    elif args.scenario:
        scenario = load_scenario_from_file(args.scenario)
    else:
        raise SystemExit(
            "run: a dynamics source is required — -s/--scenario FILE "
            "or --chaos 'crash=AGENT@T,...'"
        )
    params = parse_algo_params(args.algo_params)
    from pydcop_tpu.telemetry import session

    if args.compile_cache:
        from pydcop_tpu.ops.compile import (
            enable_persistent_compilation_cache,
        )

        enable_persistent_compilation_cache(args.compile_cache)

    # per-call supervisor: retry/degradation knobs + the plan's
    # device-layer fault kinds inject at every segment's supervised
    # chunk dispatches (engine/supervisor.py)
    from pydcop_tpu.engine.supervisor import make_supervisor, supervision

    sup = make_supervisor(
        retry_budget=args.retry_budget,
        chunk_floor=args.chunk_floor,
        on_numeric_fault=args.on_numeric_fault,
        plan=(
            chaos_plan
            if chaos_plan is not None
            and chaos_plan.device_faults_configured
            else None
        ),
    )

    try:
        with session(args.trace, args.trace_format) as tel, supervision(sup):
            result = run_dynamic(
                dcop,
                args.algo,
                params,
                scenario=scenario,
                distribution=args.distribution,
                k_target=args.ktarget,
                rounds_per_second=args.rounds_per_second,
                final_rounds=args.final_rounds,
                seed=args.seed,
                timeout=args.timeout,
                repair_algo=args.repair_algo,
                pad_policy=args.pad_policy,
            )
            result["telemetry"] = tel.summary()
    except (ValueError, ImpossibleDistributionException) as e:
        raise SystemExit(f"run: {e}")
    if chaos_plan is not None:  # replay record: spec + seed
        result["chaos"] = chaos_plan.to_meta()
    write_metrics(args, result)
    result.pop("cost_trace", None)
    result.pop("trace_subsampled", None)
    result.pop("trace_msgs", None)
    write_result(args, result)
    return 0
