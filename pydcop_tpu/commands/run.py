"""``pydcop_tpu run`` (reference: ``pydcop/commands/run.py``).

Solve a DCOP while playing a scenario of dynamic events (agent
departures/arrivals, external-variable changes), with optional
k-resilient replication + repair.  Prints the result JSON including the
event log.
"""

from __future__ import annotations

from pydcop_tpu.commands._common import (
    add_collect_arguments,
    parse_algo_params,
    write_metrics,
    write_result,
)


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "run", help="solve a DCOP while playing a dynamic scenario"
    )
    p.add_argument("dcop_files", nargs="+", help="dcop yaml file(s)")
    p.add_argument("-a", "--algo", required=True, help="algorithm name")
    p.add_argument(
        "-p", "--algo_params", action="append", default=[],
        metavar="NAME:VALUE", help="algorithm parameter (repeatable)",
    )
    p.add_argument(
        "-s", "--scenario", required=True, help="scenario yaml file"
    )
    p.add_argument(
        "-d", "--distribution", default="oneagent",
        help="distribution strategy for the initial placement",
    )
    p.add_argument(
        "-k", "--ktarget", type=int, default=1,
        help="replicas per computation (0 disables replication)",
    )
    p.add_argument(
        "--rounds_per_second", type=float, default=20.0,
        help="scenario delay seconds → engine rounds scale",
    )
    p.add_argument(
        "--final_rounds", type=int, default=100,
        help="rounds before the first and after the last event",
    )
    p.add_argument(
        "--repair_algo", default="mgm",
        help="algorithm solving the reparation DCOP",
    )
    p.add_argument("--seed", type=int, default=0)
    add_collect_arguments(p)
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.dcop.yamldcop import (
        load_dcop_from_file,
        load_scenario_from_file,
    )
    from pydcop_tpu.distribution import ImpossibleDistributionException
    from pydcop_tpu.engine.dynamic import run_dynamic

    dcop = load_dcop_from_file(
        args.dcop_files if len(args.dcop_files) > 1 else args.dcop_files[0]
    )
    scenario = load_scenario_from_file(args.scenario)
    params = parse_algo_params(args.algo_params)
    try:
        result = run_dynamic(
            dcop,
            args.algo,
            params,
            scenario=scenario,
            distribution=args.distribution,
            k_target=args.ktarget,
            rounds_per_second=args.rounds_per_second,
            final_rounds=args.final_rounds,
            seed=args.seed,
            timeout=args.timeout,
            repair_algo=args.repair_algo,
        )
    except (ValueError, ImpossibleDistributionException) as e:
        raise SystemExit(f"run: {e}")
    write_metrics(args, result)
    result.pop("cost_trace", None)
    result.pop("trace_subsampled", None)
    result.pop("trace_msgs", None)
    write_result(args, result)
    return 0
