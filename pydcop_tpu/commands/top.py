"""``pydcop_tpu top`` — live terminal view of a serving process.

Polls a ``serve --metrics_port`` exporter's ``/metrics`` and
``/healthz`` endpoints (``telemetry/export.py``,
``docs/observability.md`` "Serving observability") and renders the
serving vitals in place: health/drain state, queue depth, request /
tick / shed counters with per-interval rates, and the latency
histogram percentiles.  ``--count 1`` prints one snapshot and exits
(scriptable); the default loops until Ctrl-C.
"""

from __future__ import annotations

import json
import sys
import time


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "top",
        help="live terminal view of a running serve --metrics_port "
        "process: polls /metrics + /healthz into request/shed rates, "
        "queue depth and latency percentiles "
        "(docs/observability.md)",
    )
    p.add_argument(
        "address",
        help="the exporter address: host:port or a full http:// URL "
        "(the serving line of `pydcop_tpu serve --metrics_port` "
        "prints it)",
    )
    p.add_argument(
        "--interval", type=float, default=2.0, metavar="SECONDS",
        help="poll interval (default 2s)",
    )
    p.add_argument(
        "--count", type=int, default=0, metavar="N",
        help="stop after N polls (0 = until Ctrl-C); --count 1 is "
        "the scriptable one-shot snapshot",
    )
    p.set_defaults(func=run_cmd)


#: the headline counter rows, in display order (raw exported names —
#: the `pydcop_` prefix and `_total` suffix are added by the exporter)
_HEADLINE_COUNTERS = (
    ("service_requests", "requests"),
    ("service_ticks", "ticks"),
    ("service_dispatches", "dispatches"),
    ("service_coalesced", "coalesced"),
    ("service_shed", "shed"),
    ("service_errors", "errors"),
    ("service_replayed_replies", "replayed"),
    ("service_frames_rejected", "frames_rejected"),
    ("telemetry_flight_dumps", "flight_dumps"),
)

_HIST_ROWS = (
    ("service_queue_wait_s", "queue_wait_s"),
    ("service_latency_s", "latency_s"),
    ("service_shed_latency_s", "shed_latency_s"),
    ("service_batch_occupancy", "occupancy"),
)


def _base_url(address: str) -> str:
    if address.startswith(("http://", "https://")):
        return address.rstrip("/")
    return "http://" + address


def format_top(
    metrics: dict, health: dict, rates: dict
) -> str:
    """One rendered frame from parsed /metrics + /healthz (split out
    for tests)."""
    lines = []
    status = health.get("status", "?")
    lines.append(
        f"serve: status={status} queue_depth="
        f"{health.get('queue_depth', '?')} inflight="
        f"{health.get('inflight', '?')} sessions="
        f"{health.get('sessions', '?')}"
    )
    lines.append("")
    lines.append(f"{'counter':<18}{'total':>12}{'per_sec':>10}")
    from pydcop_tpu.telemetry.export import PREFIX

    for raw, label in _HEADLINE_COUNTERS:
        key = PREFIX + raw + "_total"
        if key not in metrics:
            continue
        rate = rates.get(key)
        lines.append(
            f"{label:<18}{int(metrics[key]):>12}"
            + (f"{rate:>10.1f}" if rate is not None else f"{'-':>10}")
        )
    hist_lines = []
    for raw, label in _HIST_ROWS:
        count_key = PREFIX + raw + "_count"
        if count_key not in metrics:
            continue
        row = f"{label:<18}{int(metrics[count_key]):>8}"
        for q in ("p50", "p90", "p99"):
            v = metrics.get(f"{PREFIX}{raw}_{q}")
            row += (
                f"  {q}={v:g}" if v is not None else f"  {q}=-"
            )
        hist_lines.append(row)
    if hist_lines:
        lines.append("")
        lines.append(f"{'histogram':<18}{'count':>8}  percentiles")
        lines.extend(hist_lines)
    return "\n".join(lines)


def run_cmd(args) -> int:
    from pydcop_tpu.telemetry.export import (
        http_get,
        parse_prometheus_text,
    )

    base = _base_url(args.address)
    if args.interval <= 0:
        raise SystemExit("top: --interval must be > 0")
    prev: dict = {}
    prev_t = None
    polls = 0
    try:
        while True:
            try:
                metrics = parse_prometheus_text(
                    http_get(base + "/metrics")
                )
                health = json.loads(http_get(base + "/healthz"))
            except (OSError, ValueError) as e:
                raise SystemExit(
                    f"top: cannot scrape {base}: {e}"
                )
            now = time.perf_counter()
            rates = {}
            if prev_t is not None:
                dt = max(now - prev_t, 1e-9)
                rates = {
                    k: (v - prev.get(k, 0.0)) / dt
                    for k, v in metrics.items()
                    if isinstance(v, (int, float))
                    and k.endswith("_total")
                }
            frame = format_top(metrics, health, rates)
            if polls and sys.stdout.isatty():
                # redraw in place on a live terminal; plain append
                # otherwise (pipes/tests get one frame per poll)
                print("\x1b[2J\x1b[H", end="")
            print(frame, flush=True)
            polls += 1
            prev, prev_t = metrics, now
            if args.count and polls >= args.count:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
