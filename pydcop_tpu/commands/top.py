"""``pydcop_tpu top`` — live terminal view of serving processes.

Polls ``serve --metrics_port`` exporters' ``/metrics`` and
``/healthz`` endpoints (``telemetry/export.py``,
``docs/observability.md`` "Serving observability") and renders the
serving vitals in place: health/drain state, queue depth, request /
tick / shed counters with per-interval rates, and the latency
histogram percentiles.  ``--count 1`` prints one snapshot and exits
(scriptable); the default loops until Ctrl-C.

Fleet mode: pass SEVERAL exporter addresses, or just the ``fleet``
router's aggregate endpoint — its ``/healthz`` carries the
per-replica roster (name, liveness, metrics address), which ``top``
expands into one row per replica plus a fleet-total row
(``docs/serving.md``, "The fleet")."""

from __future__ import annotations

import json
import sys
import time


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "top",
        help="live terminal view of a running serve --metrics_port "
        "process: polls /metrics + /healthz into request/shed rates, "
        "queue depth and latency percentiles "
        "(docs/observability.md)",
    )
    p.add_argument(
        "addresses", nargs="+", metavar="address",
        help="one or more exporter addresses: host:port or a full "
        "http:// URL (the serving line of `pydcop_tpu serve "
        "--metrics_port` prints it).  Several addresses — or a "
        "single `fleet --metrics_port` aggregate endpoint, whose "
        "roster is expanded automatically — render per-replica "
        "rows plus a fleet total",
    )
    p.add_argument(
        "--interval", type=float, default=2.0, metavar="SECONDS",
        help="poll interval (default 2s)",
    )
    p.add_argument(
        "--count", type=int, default=0, metavar="N",
        help="stop after N polls (0 = until Ctrl-C); --count 1 is "
        "the scriptable one-shot snapshot",
    )
    p.set_defaults(func=run_cmd)


#: the headline counter rows, in display order (raw exported names —
#: the `pydcop_` prefix and `_total` suffix are added by the exporter)
_HEADLINE_COUNTERS = (
    ("service_requests", "requests"),
    ("service_ticks", "ticks"),
    ("service_dispatches", "dispatches"),
    ("service_coalesced", "coalesced"),
    ("service_shed", "shed"),
    ("service_errors", "errors"),
    ("service_replayed_replies", "replayed"),
    ("service_frames_rejected", "frames_rejected"),
    # deterministic contraction work delivered — the rate column is
    # cells/s, the FAQ cost-model throughput unit (docs/performance.md)
    ("service_work_cells", "work_cells"),
    ("telemetry_flight_dumps", "flight_dumps"),
)

_HIST_ROWS = (
    ("service_queue_wait_s", "queue_wait_s"),
    ("service_latency_s", "latency_s"),
    ("service_shed_latency_s", "shed_latency_s"),
    ("service_batch_occupancy", "occupancy"),
)


def _base_url(address: str) -> str:
    if address.startswith(("http://", "https://")):
        return address.rstrip("/")
    return "http://" + address


def format_top(
    metrics: dict, health: dict, rates: dict
) -> str:
    """One rendered frame from parsed /metrics + /healthz (split out
    for tests)."""
    lines = []
    status = health.get("status", "?")
    lines.append(
        f"serve: status={status} queue_depth="
        f"{health.get('queue_depth', '?')} inflight="
        f"{health.get('inflight', '?')} sessions="
        f"{health.get('sessions', '?')}"
    )
    lines.append("")
    lines.append(f"{'counter':<18}{'total':>12}{'per_sec':>10}")
    from pydcop_tpu.telemetry.export import PREFIX

    for raw, label in _HEADLINE_COUNTERS:
        key = PREFIX + raw + "_total"
        if key not in metrics:
            continue
        rate = rates.get(key)
        lines.append(
            f"{label:<18}{int(metrics[key]):>12}"
            + (f"{rate:>10.1f}" if rate is not None else f"{'-':>10}")
        )
    hist_lines = []
    for raw, label in _HIST_ROWS:
        count_key = PREFIX + raw + "_count"
        if count_key not in metrics:
            continue
        row = f"{label:<18}{int(metrics[count_key]):>8}"
        for q in ("p50", "p90", "p99"):
            v = metrics.get(f"{PREFIX}{raw}_{q}")
            row += (
                f"  {q}={v:g}" if v is not None else f"  {q}=-"
            )
        hist_lines.append(row)
    if hist_lines:
        lines.append("")
        lines.append(f"{'histogram':<18}{'count':>8}  percentiles")
        lines.extend(hist_lines)
    return "\n".join(lines)


def _collect_rows(addresses):
    """One poll over every requested address: returns
    ``(router_health, rows)`` where ``rows`` is an ordered list of
    ``(label, metrics-or-None, health)``.  A fleet router's aggregate
    ``/healthz`` (it carries ``fleet: true`` and the replica roster)
    expands into one row per replica — scraped from each replica's
    OWN exporter; a dead or unreachable replica still gets a row, so
    the view never silently narrows during an outage."""
    from pydcop_tpu.telemetry.export import (
        http_get,
        parse_prometheus_text,
    )

    router_health = None
    rows = []
    for address in addresses:
        base = _base_url(address)
        try:
            health = json.loads(http_get(base + "/healthz"))
        except (OSError, ValueError) as e:
            raise SystemExit(f"top: cannot scrape {base}: {e}")
        roster = health.get("replicas")
        if health.get("fleet") and isinstance(roster, dict):
            router_health = health
            for name in sorted(roster):
                rep = roster[name] or {}
                maddr = rep.get("metrics")
                if not rep.get("alive", True):
                    rows.append((name, None, {"status": "dead"}))
                    continue
                if not maddr:
                    rows.append(
                        (name, None, {"status": "no-metrics"})
                    )
                    continue
                rbase = _base_url(maddr)
                try:
                    rows.append(
                        (
                            name,
                            parse_prometheus_text(
                                http_get(rbase + "/metrics")
                            ),
                            json.loads(
                                http_get(rbase + "/healthz")
                            ),
                        )
                    )
                except (OSError, ValueError):
                    rows.append(
                        (name, None, {"status": "unreachable"})
                    )
            continue
        try:
            metrics = parse_prometheus_text(
                http_get(base + "/metrics")
            )
        except (OSError, ValueError) as e:
            raise SystemExit(f"top: cannot scrape {base}: {e}")
        rows.append((address, metrics, health))
    return router_health, rows


def format_fleet_top(router_health, rows, rates) -> str:
    """The fleet frame: one row per replica plus a total (split out
    for tests).  ``rates`` maps row label → requests/sec (None on
    the first poll)."""
    from pydcop_tpu.telemetry.export import PREFIX

    lines = []
    if router_health is not None:
        dead = [
            n
            for n, rep in (router_health.get("replicas") or {}).items()
            if not (rep or {}).get("alive", True)
        ]
        lines.append(
            f"fleet: status={router_health.get('status', '?')} "
            f"replicas={len(router_health.get('replicas') or {})} "
            f"dead={sorted(dead)} "
            f"sessions={router_health.get('sessions', '?')} "
            f"requests={router_health.get('requests', '?')} "
            f"failovers={router_health.get('failovers', '?')}"
        )
        lines.append("")
    lines.append(
        f"{'replica':<12}{'status':<12}{'queue':>6}{'sess':>6}"
        f"{'requests':>10}{'req/s':>8}{'shed':>7}{'errors':>7}"
        f"{'p99_s':>9}"
    )
    tot = {"queue": 0, "sess": 0, "requests": 0, "shed": 0,
           "errors": 0}
    tot_rate = 0.0
    saw_rate = False
    for label, metrics, health in rows:
        status = (health or {}).get("status", "?")
        if metrics is None:
            lines.append(f"{label:<12}{status:<12}" + "-".rjust(6))
            continue
        queue = int((health or {}).get("queue_depth", 0))
        sess = int((health or {}).get("sessions", 0))
        reqs = int(metrics.get(
            PREFIX + "service_requests_total", 0
        ))
        shed = int(metrics.get(PREFIX + "service_shed_total", 0))
        errs = int(metrics.get(PREFIX + "service_errors_total", 0))
        p99 = metrics.get(PREFIX + "service_latency_s_p99")
        rate = rates.get(label)
        if rate is not None:
            tot_rate += rate
            saw_rate = True
        tot["queue"] += queue
        tot["sess"] += sess
        tot["requests"] += reqs
        tot["shed"] += shed
        tot["errors"] += errs
        lines.append(
            f"{label:<12}{status:<12}{queue:>6}{sess:>6}"
            f"{reqs:>10}"
            + (f"{rate:>8.1f}" if rate is not None else f"{'-':>8}")
            + f"{shed:>7}{errs:>7}"
            + (f"{p99:>9.3g}" if p99 is not None else f"{'-':>9}")
        )
    lines.append(
        f"{'TOTAL':<12}{'':<12}{tot['queue']:>6}{tot['sess']:>6}"
        f"{tot['requests']:>10}"
        + (f"{tot_rate:>8.1f}" if saw_rate else f"{'-':>8}")
        + f"{tot['shed']:>7}{tot['errors']:>7}" + f"{'':>9}"
    )
    return "\n".join(lines)


def run_cmd(args) -> int:
    if args.interval <= 0:
        raise SystemExit("top: --interval must be > 0")
    prev: dict = {}
    prev_t = None
    polls = 0
    try:
        while True:
            router_health, rows = _collect_rows(args.addresses)
            now = time.perf_counter()
            fleet_view = router_health is not None or len(rows) > 1
            if not fleet_view:
                label, metrics, health = rows[0]
                rates = {}
                if prev_t is not None:
                    dt = max(now - prev_t, 1e-9)
                    rates = {
                        k: (v - prev.get(label, {}).get(k, 0.0))
                        / dt
                        for k, v in metrics.items()
                        if isinstance(v, (int, float))
                        and k.endswith("_total")
                    }
                frame = format_top(metrics, health, rates)
                prev = {label: metrics}
            else:
                from pydcop_tpu.telemetry.export import PREFIX

                req_key = PREFIX + "service_requests_total"
                rates = {}
                cur = {}
                for label, metrics, _health in rows:
                    if metrics is None:
                        continue
                    cur[label] = metrics
                    if prev_t is not None and label in prev:
                        dt = max(now - prev_t, 1e-9)
                        rates[label] = (
                            metrics.get(req_key, 0.0)
                            - prev[label].get(req_key, 0.0)
                        ) / dt
                frame = format_fleet_top(
                    router_health, rows, rates
                )
                prev = cur
            if polls and sys.stdout.isatty():
                # redraw in place on a live terminal; plain append
                # otherwise (pipes/tests get one frame per poll)
                print("\x1b[2J\x1b[H", end="")
            print(frame, flush=True)
            polls += 1
            prev_t = now
            if args.count and polls >= args.count:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
