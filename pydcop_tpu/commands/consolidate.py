"""``pydcop_tpu consolidate`` — placeholder, implemented in a later milestone
(reference: ``pydcop/commands/consolidate.py``)."""


def set_parser(subparsers) -> None:
    p = subparsers.add_parser("consolidate", help="(not yet implemented)")
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    raise SystemExit("consolidate: not yet implemented in this build")
