"""``pydcop_tpu consolidate`` (reference: ``pydcop/commands/consolidate.py``).

Merge result CSVs from batch runs into one file, optionally aggregating
numeric columns (mean/min/max) grouped by key columns.
"""

from __future__ import annotations

import csv
import glob as globmod
import json
import statistics
from typing import Dict, List


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "consolidate", help="merge/aggregate batch result CSVs"
    )
    p.add_argument(
        "csv_files", nargs="+", help="result CSV files (globs allowed)"
    )
    p.add_argument(
        "--result_file", default="consolidated.csv", help="merged CSV"
    )
    p.add_argument(
        "--group_by", nargs="*", default=None,
        help="aggregate numeric columns grouped by these columns",
    )
    p.add_argument(
        "--aggregate", choices=["mean", "min", "max"], default="mean"
    )
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    files: List[str] = []
    for pattern in args.csv_files:
        matches = sorted(globmod.glob(pattern))
        files.extend(matches if matches else [pattern])

    rows: List[Dict[str, str]] = []
    fields: List[str] = []
    for path in files:
        with open(path, newline="") as f:
            reader = csv.DictReader(f)
            for name in reader.fieldnames or []:
                if name not in fields:
                    fields.append(name)
            rows.extend(reader)

    if args.group_by:
        missing = [c for c in args.group_by if c not in fields]
        if missing:
            raise SystemExit(f"consolidate: unknown column(s) {missing}")
        numeric = [
            c
            for c in fields
            if c not in args.group_by and _is_numeric_col(rows, c)
        ]
        agg_fn = {
            "mean": statistics.fmean,
            "min": min,
            "max": max,
        }[args.aggregate]
        groups: Dict[tuple, List[Dict[str, str]]] = {}
        for row in rows:
            groups.setdefault(
                tuple(row.get(c, "") for c in args.group_by), []
            ).append(row)
        out_fields = list(args.group_by) + numeric + ["n_runs", "n_errors"]
        out_rows = []
        for gkey, grows in sorted(groups.items()):
            out = dict(zip(args.group_by, gkey))
            # error rows are excluded from the aggregates and surfaced
            # in n_errors, so a mean never silently hides failed runs
            ok_rows = [
                r
                for r in grows
                if not r.get("status", "").startswith("error")
            ]
            for c in numeric:
                vals = [
                    float(r[c])
                    for r in ok_rows
                    if r.get(c) not in (None, "")
                ]
                out[c] = agg_fn(vals) if vals else ""
            out["n_runs"] = len(ok_rows)
            out["n_errors"] = len(grows) - len(ok_rows)
            out_rows.append(out)
        fields, rows = out_fields, out_rows

    with open(args.result_file, "w", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=fields)
        writer.writeheader()
        for row in rows:
            writer.writerow(row)
    print(
        json.dumps(
            {
                "files": len(files),
                "rows": len(rows),
                "result_file": args.result_file,
            }
        )
    )
    return 0


def _is_numeric_col(rows: List[Dict[str, str]], col: str) -> bool:
    seen = False
    for r in rows:
        v = r.get(col)
        if v in (None, ""):
            continue
        seen = True
        try:
            float(v)
        except ValueError:
            return False
    return seen
