"""``pydcop_tpu lint`` — run graftlint, the AST-based invariant
linter (``tools/graftlint/``, ``docs/linting.md``).

Machine-checks the contracts reviewer vigilance kept missing: the
jax-free import surface, determinism purity of seeded scopes,
chaos-spec symmetry across entry points, telemetry/doc drift, and
trace-key stability.  Findings diff against the recorded baseline
(``tools/graftlint_baseline.json``); exit 1 on any NEW finding.

The linter lives under ``tools/`` (it lints the repository, it is not
part of the package), so this command needs a source checkout — it
locates ``tools/graftlint`` next to the ``pydcop_tpu`` package.
Parser and scan are stdlib-``ast``-only: linting the jax-free surface
never imports jax (``tests/test_import_time.py`` pins this).
"""

from __future__ import annotations

import sys
from pathlib import Path


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "lint",
        help="run graftlint: machine-check determinism, import-"
        "hygiene, chaos-symmetry, telemetry and trace-key contracts "
        "(docs/linting.md)",
    )
    p.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable findings (file, line, rule id, "
        "message) for CI annotation",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="pin the current findings into "
        "tools/graftlint_baseline.json (existing justifications "
        "kept; new entries marked TODO for review)",
    )
    p.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="baseline file (default: tools/graftlint_baseline.json)",
    )
    p.add_argument(
        "--root", default=None, metavar="DIR",
        help="project root (default: the checkout containing the "
        "pydcop_tpu package)",
    )
    p.add_argument(
        "--rule", action="append", default=None, metavar="RULE_ID",
        help="run only this rule (repeatable; see docs/linting.md "
        "for the catalog)",
    )
    p.set_defaults(func=run_cmd)


def _find_root(explicit) -> Path:
    if explicit:
        return Path(explicit).resolve()
    import pydcop_tpu

    return Path(pydcop_tpu.__file__).resolve().parent.parent


def run_cmd(args) -> int:
    root = _find_root(args.root)
    tools_dir = root / "tools"
    if not (tools_dir / "graftlint" / "__init__.py").is_file():
        raise SystemExit(
            f"lint: {tools_dir}/graftlint not found — graftlint runs "
            "from a source checkout (pass --root, or run from the "
            "repository)"
        )
    if str(tools_dir) not in sys.path:
        sys.path.insert(0, str(tools_dir))
    from graftlint.cli import run as graftlint_run

    # reuse the tool's own runner so `pydcop_tpu lint` and
    # `python tools/graftlint/cli.py` cannot drift apart
    args.root = str(root)
    return graftlint_run(args)
