"""``pydcop_tpu agent`` (reference: ``pydcop/commands/agent.py``).

Start one or more agent processes that register with an orchestrator,
receive their deployment, and participate in the sharded SPMD solve as
one ``jax.distributed`` process each.  With several ``--names``, one OS
subprocess is forked per agent (the reference's multi-agent form).
"""

from __future__ import annotations

import json
import sys


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "agent",
        help="join an orchestrator-coordinated cross-process run",
    )
    p.add_argument(
        "--names", "-n", nargs="+", required=True,
        help="agent name(s); several names fork one process each",
    )
    p.add_argument(
        "--orchestrator", "-o", required=True, metavar="HOST:PORT",
        help="orchestrator management address",
    )
    p.add_argument(
        "--retry_for", type=float, default=30.0,
        help="seconds to keep retrying the initial connection",
    )
    p.add_argument(
        "--msg_log", default=None, metavar="FILE",
        help="(--runtime host) dump every delivered message's full "
        "content to FILE as JSON lines — the reference Messaging's "
        "per-message log; several --names get FILE.<agent> each",
    )
    p.add_argument(
        "--chaos", default=None, metavar="SPEC",
        help="(--runtime host) apply a local fault-injection plan to "
        "THIS agent's outbound message plane (overrides any plan the "
        "orchestrator ships; spec format: docs/faults.md)",
    )
    p.add_argument(
        "--chaos_seed", type=int, default=0,
        help="seed for the --chaos fault plan",
    )
    p.add_argument(
        "--runtime", choices=["spmd", "host"], default="spmd",
        help="must match the orchestrator's --runtime (spmd: sharded "
        "batched solve as a jax.distributed process; host: "
        "message-driven computations over TCP)",
    )
    from pydcop_tpu.commands._common import add_trace_arguments

    add_trace_arguments(p)
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    if args.msg_log and args.runtime != "host":
        raise SystemExit(
            "--msg_log records delivered message contents — only the "
            "host runtime has per-message delivery (--runtime host); "
            "the spmd runtime runs the fused batched engine"
        )
    if args.chaos and args.runtime != "host":
        raise SystemExit(
            "--chaos injects message-plane faults — only the host "
            "runtime has a per-agent message plane (--runtime host)"
        )
    if args.chaos:
        from pydcop_tpu.faults import FaultPlan, FaultSpecError

        try:
            plan = FaultPlan.from_spec(args.chaos, args.chaos_seed)
        except FaultSpecError as e:
            raise SystemExit(f"agent: {e}")
        if plan.wire_faults_configured:
            # a silently-inert clause would record the spec as
            # applied while injecting nothing
            raise SystemExit(
                "agent: wire-level chaos kinds (conn_drop/"
                "slow_client/frame_corrupt) inject at the solver "
                "service's frame loop — use `pydcop_tpu serve "
                "--chaos` (docs/serving.md)"
            )
        if plan.device_faults_configured:
            # same inert-clause rule for the device layer: a host
            # agent has no supervised device dispatch to inject into
            raise SystemExit(
                "agent: device-layer chaos kinds (device_oom/"
                "device_oom_bytes/device_transient/nan_inject) "
                "inject at the batched engine's supervised dispatch "
                "— use `solve`/`run --chaos` (docs/faults.md)"
            )
        if plan.fleet_faults_configured:
            raise SystemExit(
                "agent: fleet-level chaos kinds (replica_kill) act "
                "on a replicated serving fleet's processes — use "
                "`pydcop_tpu fleet --chaos` (docs/faults.md)"
            )
    if len(args.names) > 1:
        # one OS process per agent: each is an independent
        # jax.distributed participant, so fork real subprocesses
        import subprocess

        procs = [
            subprocess.Popen(
                [
                    sys.executable, "-m", "pydcop_tpu", "agent",
                    "--names", name,
                    "--orchestrator", args.orchestrator,
                    "--retry_for", str(args.retry_for),
                    "--runtime", args.runtime,
                ]
                + (
                    ["--msg_log", f"{args.msg_log}.{name}"]
                    if args.msg_log
                    else []
                )
                + (
                    [
                        "--chaos", args.chaos,
                        "--chaos_seed", str(args.chaos_seed),
                    ]
                    if args.chaos
                    else []
                )
                + (
                    [
                        "--trace", f"{args.trace}.{name}",
                        "--trace_format", args.trace_format,
                    ]
                    if args.trace
                    else []
                )
            )
            for name in args.names
        ]
        rc = 0
        for p in procs:
            rc = rc or p.wait()
        return rc

    # each agent process traces into its own file (the telemetry
    # session is process-local by design, docs/observability.md)
    from pydcop_tpu.telemetry import session

    if args.runtime == "host":
        from pydcop_tpu.infrastructure.hostnet import run_host_agent

        with session(args.trace, args.trace_format):
            result = run_host_agent(
                args.names[0], args.orchestrator,
                retry_for=args.retry_for,
                msg_log=args.msg_log,
                chaos=args.chaos, chaos_seed=args.chaos_seed,
            )
        print(json.dumps(result))
        return 0

    from pydcop_tpu.infrastructure.orchestrator import run_agent

    with session(args.trace, args.trace_format):
        result = run_agent(
            args.orchestrator, args.names[0], retry_for=args.retry_for
        )
    print(
        json.dumps(
            {
                "agent": args.names[0],
                # elastic-supervisor results carry no cost/cycle (the
                # orchestrator assembles those); static runs do
                "cost": result.get("cost"),
                "cycle": result.get("cycle"),
                "status": result.get("status"),
                "deploys": result.get("deploys"),
            }
        )
    )
    return 0
