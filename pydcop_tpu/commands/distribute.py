"""``pydcop_tpu distribute`` — placeholder, implemented in a later milestone
(reference: ``pydcop/commands/distribute.py``)."""


def set_parser(subparsers) -> None:
    p = subparsers.add_parser("distribute", help="(not yet implemented)")
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    raise SystemExit("distribute: not yet implemented in this build")
