"""``pydcop_tpu distribute`` (reference: ``pydcop/commands/distribute.py``).

Compute a computation → agent placement offline and print it as JSON
with its cost under the strategy's objective.  With ``--output`` the
reference-style ``distribution:`` yaml mapping is written to the file
(the JSON result still goes to stdout).
"""

from __future__ import annotations

import json

from pydcop_tpu.commands._common import load_dcop_and_graph


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "distribute",
        help="compute a computation→agent placement "
        "(--output writes the yaml mapping, JSON goes to stdout)",
    )
    p.add_argument("dcop_files", nargs="+", help="dcop yaml file(s)")
    p.add_argument(
        "-d", "--distribution", required=True,
        help="distribution strategy (oneagent | adhoc | heur_comhost | "
        "ilp_fgdp | ilp_compref)",
    )
    p.add_argument(
        "-g", "--graph",
        help="graph model; required unless --algo is given",
    )
    p.add_argument(
        "-a", "--algo",
        help="algorithm name; picks the graph model and provides the "
        "memory/communication footprint callbacks",
    )
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    import yaml

    from pydcop_tpu.distribution import (
        ImpossibleDistributionException,
        load_distribution_module,
    )

    try:
        dcop, graph, _model, algo_module = load_dcop_and_graph(args)
        dist_module = load_distribution_module(args.distribution)

        computation_memory = getattr(algo_module, "computation_memory", None)
        communication_load = getattr(algo_module, "communication_load", None)
        from pydcop_tpu.distribution import compute_distribution

        distribution = compute_distribution(
            dist_module,
            graph,
            dcop.agents.values(),
            hints=dcop.dist_hints,
            computation_memory=computation_memory,
            communication_load=communication_load,
        )
    except (ValueError, ImpossibleDistributionException) as e:
        raise SystemExit(f"distribute: {e}")

    result = {"distribution": distribution.mapping}
    if hasattr(dist_module, "distribution_cost"):
        total, comm, hosting = dist_module.distribution_cost(
            distribution,
            graph,
            dcop.agents.values(),
            computation_memory,
            communication_load,
        )
        result["cost"] = total
        result["communication_cost"] = comm
        result["hosting_cost"] = hosting

    if args.output:
        with open(args.output, "w") as f:
            yaml.safe_dump({"distribution": distribution.mapping}, f)
    print(json.dumps(result, indent=2, default=str))
    return 0
