"""``pydcop_tpu bench-compare`` — statistical comparison of measurements.

Two modes (``docs/performance.md`` "Reading the trajectory"):

- ``--pairs FILE``: a JSON doc of *paired interleaved samples*
  (``{"baseline": [...], "candidate": [...], "higher_is_better":
  true}``) is run through the deterministic comparator
  (``tools/benchkeeper/stats.py``: sign test + seeded-bootstrap CI on
  paired ratios) and gets a full ``regression|improvement|noise``
  verdict.  Seeded, so two runs over the same file are bit-identical.
  Exit code 1 on a ``regression`` verdict (CI-friendly).

- ``--baseline rNN --candidate rMM``: ledger rounds are compared as
  fingerprint-checked *point ratios only* — cross-round samples were
  never interleaved, so no statistical verdict is claimed, and a
  fingerprint mismatch on any comparability field refuses the
  comparison outright rather than printing a cross-environment number.
"""

from __future__ import annotations

import json
import sys

from pydcop_tpu.commands.bench_history import _find_root, import_benchkeeper


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "bench-compare",
        help="compare measurements: paired-sample verdicts "
        "(regression|improvement|noise) or fingerprint-checked round "
        "ratios (docs/performance.md)",
    )
    p.add_argument(
        "--pairs", default=None, metavar="FILE",
        help="JSON doc with paired interleaved samples: "
        '{"baseline": [...], "candidate": [...], '
        '"higher_is_better": true} — full statistical verdict',
    )
    p.add_argument(
        "--baseline", default=None, metavar="ROUND",
        help="ledger round to compare from (e.g. r08)",
    )
    p.add_argument(
        "--candidate", default=None, metavar="ROUND",
        help="ledger round to compare to (e.g. r09)",
    )
    p.add_argument(
        "--stage", default=None, metavar="STAGE",
        help="restrict round comparison to one stage",
    )
    p.add_argument(
        "--metric", default=None, metavar="METRIC",
        help="restrict round comparison to one metric",
    )
    p.add_argument(
        "--ledger", default=None, metavar="FILE",
        help="ledger path (default: <root>/benchdata/ledger.jsonl)",
    )
    p.add_argument(
        "--seed", type=int, default=None,
        help="bootstrap seed (default: the comparator's pinned seed)",
    )
    p.add_argument(
        "--alpha", type=float, default=None,
        help="sign-test significance level (default 0.05)",
    )
    p.add_argument(
        "--noise_floor", type=float, default=None,
        help="practical-significance floor on |median ratio - 1| "
        "(default 0.05)",
    )
    p.add_argument(
        "--n_boot", type=int, default=None,
        help="bootstrap resamples (default 2000)",
    )
    p.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable result",
    )
    p.add_argument(
        "--root", default=None, metavar="DIR",
        help="project root (default: the checkout containing the "
        "pydcop_tpu package)",
    )
    p.set_defaults(func=run_cmd)


def _emit(args, doc: dict, text: str) -> None:
    if args.as_json:
        out = json.dumps(doc, indent=2, sort_keys=True)
    else:
        out = text
    print(out)
    if getattr(args, "output", None):
        with open(args.output, "w") as f:
            f.write(json.dumps(doc, indent=2, sort_keys=True) + "\n")


def run_cmd(args) -> int:
    root = _find_root(args.root)
    bk_ledger, bk_history = import_benchkeeper(root)

    if args.pairs and (args.baseline or args.candidate):
        print(
            "bench-compare: --pairs and --baseline/--candidate are "
            "mutually exclusive", file=sys.stderr,
        )
        return 2

    if args.pairs:
        try:
            with open(args.pairs) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"bench-compare: cannot read {args.pairs}: {e}",
                  file=sys.stderr)
            return 2
        kwargs = {}
        if args.seed is not None:
            kwargs["seed"] = args.seed
        if args.alpha is not None:
            kwargs["alpha"] = args.alpha
        if args.noise_floor is not None:
            kwargs["noise_floor"] = args.noise_floor
        if args.n_boot is not None:
            kwargs["n_boot"] = args.n_boot
        try:
            result = bk_history.compare_pairs_doc(doc, **kwargs)
        except ValueError as e:
            print(f"bench-compare: {e}", file=sys.stderr)
            return 2
        _emit(args, result, bk_history.format_verdict(result))
        return 1 if result["verdict"] == "regression" else 0

    if not (args.baseline and args.candidate):
        print(
            "bench-compare: need either --pairs FILE or "
            "--baseline ROUND --candidate ROUND", file=sys.stderr,
        )
        return 2

    path = args.ledger or str(root / bk_ledger.LEDGER_RELPATH)
    rows = bk_ledger.read_ledger(path)
    if not rows:
        print(
            f"bench-compare: no ledger rows at {path} "
            "(run bench-history --rebuild to seed it)", file=sys.stderr,
        )
        return 2
    result = bk_history.compare_rounds(
        rows, args.baseline, args.candidate,
        stage=args.stage, metric=args.metric,
    )
    _emit(args, result, bk_history.format_compare_rounds(result))
    if not result["entries"]:
        return 2
    return 0
