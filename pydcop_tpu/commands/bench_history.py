"""``pydcop_tpu bench-history`` — render the performance trajectory.

Reads the normalized ledger (``benchdata/ledger.jsonl``, see
``docs/performance.md`` "Reading the trajectory") and prints per-stage
sparkline trends with ratio-chain normalization across environment
fingerprints, the per-round status line, and per-backend staleness —
any backend whose newest row is older than ``--stale_hours`` (default
72h) is flagged STALE instead of going quietly out of date.

``--rebuild`` regenerates the ledger from the historic artifacts
(``BENCH_r*.json`` + ``BENCH_TPU_LOG.jsonl``); the ledger is derived
data, so a rebuild is always safe.

Like ``lint``, this drives a tool that lives under ``tools/``
(``tools/benchkeeper/``) and therefore needs a source checkout.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "bench-history",
        help="render the bench trajectory: sparkline trends, round "
        "status, per-backend staleness (docs/performance.md)",
    )
    p.add_argument(
        "--ledger", default=None, metavar="FILE",
        help="ledger path (default: <root>/benchdata/ledger.jsonl)",
    )
    p.add_argument(
        "--stage", default=None, metavar="STAGE",
        help="show only this stage, with per-point detail",
    )
    p.add_argument(
        "--stale_hours", type=float, default=72.0, metavar="H",
        help="flag a backend STALE when its newest row is older than "
        "this many hours (default 72)",
    )
    p.add_argument(
        "--now", default=None, metavar="TS",
        help="compute staleness against this UTC timestamp "
        "(%%Y-%%m-%%dT%%H:%%M:%%SZ) instead of the wall clock — for "
        "reproducible output in tests",
    )
    p.add_argument(
        "--rebuild", action="store_true",
        help="regenerate the ledger from BENCH_r*.json + "
        "BENCH_TPU_LOG.jsonl before rendering",
    )
    p.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable report (rows, rounds, freshness)",
    )
    p.add_argument(
        "--root", default=None, metavar="DIR",
        help="project root (default: the checkout containing the "
        "pydcop_tpu package)",
    )
    p.set_defaults(func=run_cmd)


def _find_root(explicit) -> Path:
    if explicit:
        return Path(explicit).resolve()
    import pydcop_tpu

    return Path(pydcop_tpu.__file__).resolve().parent.parent


def import_benchkeeper(root: Path):
    """Put ``tools/`` on the path and import benchkeeper — shared by
    bench-history and bench-compare so the lookup cannot drift."""
    tools_dir = root / "tools"
    if not (tools_dir / "benchkeeper" / "__init__.py").is_file():
        raise SystemExit(
            f"bench-history: {tools_dir}/benchkeeper not found — the "
            "bench tooling runs from a source checkout (pass --root, "
            "or run from the repository)"
        )
    if str(tools_dir) not in sys.path:
        sys.path.insert(0, str(tools_dir))
    import benchkeeper.history
    import benchkeeper.ledger

    return benchkeeper.ledger, benchkeeper.history


def run_cmd(args) -> int:
    root = _find_root(args.root)
    bk_ledger, bk_history = import_benchkeeper(root)
    path = args.ledger or str(root / bk_ledger.LEDGER_RELPATH)
    if args.rebuild:
        rows = bk_ledger.seed_rows(str(root))
        n = bk_ledger.write_ledger(path, rows)
        print(f"rebuilt {path}: {n} rows", file=sys.stderr)
    rows = bk_ledger.read_ledger(path)
    if not rows:
        print(
            f"bench-history: no ledger rows at {path} "
            "(run with --rebuild to seed it from BENCH_r*.json)",
            file=sys.stderr,
        )
        return 1
    now_epoch = (
        bk_ledger.parse_ts(args.now) if args.now else time.time()
    )
    if args.as_json:
        doc = {
            "ledger": path,
            "n_rows": len(rows),
            "rounds": bk_history.rounds_summary(rows),
            "freshness": bk_history.stale_backends(
                rows, now_epoch=now_epoch, stale_hours=args.stale_hours
            ),
        }
        text = json.dumps(doc, indent=2, sort_keys=True)
        print(text)
        if getattr(args, "output", None):
            with open(args.output, "w") as f:
                f.write(text + "\n")
        return 0
    print(bk_history.history_report(
        rows,
        now_epoch=now_epoch,
        stale_hours=args.stale_hours,
        stage=args.stage,
    ))
    return 0
