"""``pydcop_tpu generate`` — placeholder, implemented in a later milestone
(reference: ``pydcop/commands/generate.py``)."""


def set_parser(subparsers) -> None:
    p = subparsers.add_parser("generate", help="(not yet implemented)")
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    raise SystemExit("generate: not yet implemented in this build")
