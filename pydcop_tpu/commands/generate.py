"""``pydcop_tpu generate`` (reference: ``pydcop/commands/generate.py``).

Benchmark-problem generators, one sub-subcommand per family:
``graph_coloring``, ``ising``, ``meeting_scheduling``, ``secp``,
``agents``.  Each writes a dcop (or agents) yaml to stdout/--output.
"""

from __future__ import annotations

import argparse
import importlib

from pydcop_tpu.commands.generators import GENERATORS


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "generate", help="generate benchmark DCOP instances"
    )
    sub = p.add_subparsers(dest="generator", required=True)
    # accept the global flags (--output, -t, ...) after the generator
    # name as well, mirroring the top-level CLI wiring
    from pydcop_tpu.cli import _SubparsersProxy, _add_global_args

    parent = argparse.ArgumentParser(add_help=False)
    _add_global_args(parent, suppress=True)
    proxy = _SubparsersProxy(sub, [parent])
    for name in GENERATORS:
        mod = importlib.import_module(
            f"pydcop_tpu.commands.generators.{name}"
        )
        mod.set_parser(proxy)
