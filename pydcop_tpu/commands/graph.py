"""``pydcop_tpu graph`` (reference: ``pydcop/commands/graph.py``).

Build the computation graph for a DCOP and print node/edge/density
statistics as JSON.
"""

from __future__ import annotations

import json

from pydcop_tpu.commands._common import write_result


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "graph", help="compute computation-graph statistics for a dcop"
    )
    p.add_argument("dcop_files", nargs="+", help="dcop yaml file(s)")
    p.add_argument(
        "-g", "--graph",
        help="graph model (constraints_hypergraph | factor_graph | "
        "pseudotree | ordered_graph)",
    )
    p.add_argument(
        "-a", "--algo",
        help="algorithm name (used to pick the graph model if -g absent)",
    )
    p.add_argument(
        "--display", action="store_true",
        help="also dump the full node/link lists",
    )
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.commands._common import load_dcop_and_graph

    _dcop, g, graph_model, _algo = load_dcop_and_graph(args)
    result = {
        "graph": graph_model,
        "nodes": len(g.nodes),
        "links": len(g.links),
        "density": g.density(),
    }
    if args.display:
        result["node_list"] = [n.name for n in g.nodes]
        result["link_list"] = [
            {"type": l.type, "nodes": list(l.nodes)} for l in g.links
        ]
    write_result(args, result)
    return 0
