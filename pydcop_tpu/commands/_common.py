"""Shared CLI helpers: algo-param parsing, metrics/result output."""

from __future__ import annotations

import csv
import json
import sys
from typing import Any, Dict, List


def load_dcop_and_graph(args):
    """Shared --graph/--algo resolution + dcop loading for the graph
    and distribute commands.  Returns (dcop, graph, algo_module)."""
    from pydcop_tpu.dcop.yamldcop import load_dcop_from_file
    from pydcop_tpu.graphs import load_graph_module

    if not args.graph and not args.algo:
        raise SystemExit(f"{args.command}: provide --graph or --algo")
    algo_module = None
    graph_model = args.graph
    if args.algo:
        from pydcop_tpu.algorithms import load_algorithm_module

        algo_module = load_algorithm_module(args.algo)
        if graph_model is None:
            graph_model = algo_module.GRAPH_TYPE
    dcop = load_dcop_from_file(
        args.dcop_files if len(args.dcop_files) > 1 else args.dcop_files[0]
    )
    graph = load_graph_module(graph_model).build_computation_graph(dcop)
    return dcop, graph, graph_model, algo_module


def parse_algo_params(items: List[str]) -> Dict[str, str]:
    """Parse repeated ``name:value`` CLI parameters."""
    out: Dict[str, str] = {}
    for item in items:
        if ":" not in item:
            raise SystemExit(
                f"--algo_params expects name:value, got {item!r}"
            )
        name, value = item.split(":", 1)
        out[name.strip()] = value.strip()
    return out


def add_trace_arguments(parser) -> None:
    """``--trace``/``--trace_format``: structured telemetry trace
    output (``pydcop_tpu.telemetry``, ``docs/observability.md``)."""
    parser.add_argument(
        "--trace", type=str, default=None, metavar="FILE",
        help="write a structured telemetry trace (cycle/phase spans, "
        "jit compiles, message + injected-fault events) to FILE; "
        "inspect with `pydcop_tpu trace-summary FILE`",
    )
    parser.add_argument(
        "--trace_format", choices=["jsonl", "chrome"], default="jsonl",
        help="trace file format: jsonl (one record per line, the "
        "trace-summary input) or chrome (trace_event JSON for "
        "chrome://tracing / Perfetto)",
    )


def add_supervisor_arguments(parser) -> None:
    """``--retry_budget``/``--chunk_floor``/``--on_numeric_fault``:
    the supervised device-dispatch knobs of the batched engine
    (``engine/supervisor.py``, ``docs/faults.md``)."""
    parser.add_argument(
        "--retry_budget", type=int, default=None, metavar="N",
        help="transient device failures retry up to N times per "
        "dispatch (seeded deterministic backoff; default 2, 0 turns "
        "retries off) — batched engine only",
    )
    parser.add_argument(
        "--chunk_floor", type=int, default=None, metavar="ROUNDS",
        help="smallest chunk size the device-OOM degradation ladder "
        "may halve down to before the run is declared over capacity "
        "(default 8) — batched engine only",
    )
    parser.add_argument(
        "--on_numeric_fault", choices=["quarantine", "raise"],
        default=None,
        help="NaN-poisoned run/instance handling: quarantine (report "
        "the last-finite anytime best with status=degraded — for "
        "solve --many only the poisoned instance degrades, the rest "
        "of its group finishes untouched; default) or raise (fail "
        "the call) — batched engine only",
    )


def add_collect_arguments(parser) -> None:
    parser.add_argument(
        "--collect_on",
        choices=["cycle_change", "value_change", "period"],
        default="cycle_change",
        help="metric collection mode",
    )
    parser.add_argument(
        "--period", type=float, default=None,
        help="collection period (seconds), for --collect_on period",
    )
    parser.add_argument(
        "--run_metrics", type=str, default=None,
        help="write per-cycle metrics to this CSV file",
    )
    parser.add_argument(
        "--end_metrics", type=str, default=None,
        help="append end-of-run metrics to this CSV file",
    )


def write_metrics(args, result: Dict[str, Any]) -> None:
    """Write the run/end metric CSVs (reference ``--collect_on`` modes).

    - ``cycle_change``: one row per engine round (cycle).
    - ``value_change``: only rounds whose cost differs from the
      previous one (the anytime-improvement stream).
    - ``period``: rows sampled every ``--period`` seconds; the batched
      engine fuses rounds into chunks, so per-round timestamps are
      interpolated uniformly over the measured wall-clock time.
    """
    trace = result.get("cost_trace") or []
    if getattr(args, "run_metrics", None):
        n = len(trace)
        total_time = float(result.get("time", 0.0) or 0.0)
        msgs_total = int(result.get("msg_count", 0) or 0)
        cycles_total = int(result.get("cycle", n) or n)
        # on --resume, the trace covers only the new rounds: label
        # cycles from where the checkpoint left off and keep msg_count
        # cumulative over the WHOLE run (cycle and msg_count in the
        # printed JSON are whole-run too)
        first_cycle = cycles_total - n
        per_round_msgs = msgs_total / cycles_total if cycles_total else 0
        # the host runtimes SUBSAMPLE their anytime trace (one entry
        # per snapshot, not per cycle): label those proportionally or
        # the whole history reads as the run's final n cycles
        subsampled = bool(result.get("trace_subsampled"))
        # host runtimes record the ACTUAL delivered count per snapshot
        # (trace_msgs); only fall back to the proportional
        # reconstruction for traces that predate it
        msgs_at = result.get("trace_msgs") or []
        exact = len(msgs_at) == n

        def row(i):
            if exact:
                # host cycle == delivered messages (async analogue of
                # rounds), so both columns come straight off the record
                cyc, msgs = msgs_at[i], msgs_at[i]
            elif subsampled:
                cyc = max(1, round(cycles_total * (i + 1) / n)) if n else 0
                msgs = int(per_round_msgs * cyc)
            else:
                cyc = first_cycle + i + 1
                msgs = int(per_round_msgs * cyc)
            return [
                round(total_time * (i + 1) / n, 6) if n else 0.0,
                cyc,
                trace[i],
                msgs,
            ]

        mode = getattr(args, "collect_on", "cycle_change")
        rows = []
        if mode == "value_change":
            prev = None
            for i, c in enumerate(trace):
                if prev is None or c != prev:
                    rows.append(row(i))
                prev = c
        elif mode == "period":
            period = getattr(args, "period", None) or 1.0
            if period <= 0:
                raise SystemExit("--period must be > 0")
            next_t = period
            for i in range(n):
                t = total_time * (i + 1) / n
                if t >= next_t or i == n - 1:
                    rows.append(row(i))
                    # advance past t, not by one period: one long
                    # interval must not make later rows fire every round
                    while next_t <= t:
                        next_t += period
        else:  # cycle_change
            rows = [row(i) for i in range(n)]
        with open(args.run_metrics, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["time", "cycle", "cost", "msg_count"])
            w.writerows(rows)
    if getattr(args, "end_metrics", None):
        import os

        # NOTE the run/end asymmetry (documented in docs/cli.md):
        # --run_metrics describes ONE run and truncates ("w"); in
        # contrast --end_metrics accumulates one row per run across
        # invocations ("a").  The header goes in only when the file is
        # being created (or is empty) — never into the middle of an
        # existing file, so legacy header-less files keep appending
        # data rows instead of getting a header wedged mid-stream.
        needs_header = (
            not os.path.exists(args.end_metrics)
            or os.path.getsize(args.end_metrics) == 0
        )
        with open(args.end_metrics, "a", newline="") as f:
            w = csv.writer(f)
            if needs_header:
                w.writerow(
                    ["status", "cost", "cycle", "msg_count", "time"]
                )
            w.writerow(
                [
                    result.get("status"),
                    result.get("cost"),
                    result.get("cycle"),
                    result.get("msg_count"),
                    result.get("time"),
                ]
            )


def write_result(args, result: Dict[str, Any]) -> None:
    out = json.dumps(result, indent=2, default=str)
    print(out)
    if getattr(args, "output", None):
        with open(args.output, "w") as f:
            f.write(out)
