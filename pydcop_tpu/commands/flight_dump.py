"""``pydcop_tpu flight-dump`` — render a flight-recorder dump.

Reads the atomic dump a serving process wrote on a degraded / shed /
unrecoverable / drain / SIGTERM trigger (``telemetry/flightrec.py``,
``serve --flight_dump``) and prints the trigger, the triggering
request's trace id, and the recent span/event/counter timeline — the
triggering request's own records flagged with ``*``.  See
``docs/observability.md``, "Serving observability".
"""

from __future__ import annotations

import json


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "flight-dump",
        help="render a flight-recorder dump file (written by serve "
        "--flight_dump on degraded/shed/drain triggers) as a "
        "timeline, the triggering request flagged "
        "(docs/observability.md)",
    )
    p.add_argument("dump_file", help="flight dump file (JSON)")
    p.add_argument(
        "--tail", type=int, default=0, metavar="N",
        help="only show the newest N ring records (0 = all)",
    )
    p.add_argument(
        "--json", action="store_true", dest="as_json",
        help="print the raw dump document as JSON instead of the "
        "rendered timeline",
    )
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.telemetry.flightrec import format_dump, load_dump

    try:
        doc = load_dump(args.dump_file)
    except (OSError, ValueError) as e:
        raise SystemExit(f"flight-dump: {e}")
    out = (
        json.dumps(doc, indent=2, default=str)
        if args.as_json
        else format_dump(doc, tail=args.tail)
    )
    print(out)
    if getattr(args, "output", None):
        with open(args.output, "w") as f:
            f.write(out + "\n")
    return 0
