"""Graph-coloring problem generator.

Role-equivalent to the reference's
``pydcop/commands/generators/graphcoloring.py``: soft graph coloring on
random (Erdős–Rényi), grid, or scale-free (Barabási–Albert) graphs.
Each edge is a binary constraint penalizing equal colors; with
``--soft`` the penalty is a random weight, and ``--noise`` adds small
per-variable value preferences (``VariableNoisyCostFunc``) to break
symmetry, as in the reference's benchmark instances.
"""

from __future__ import annotations

import random

from pydcop_tpu.commands.generators._common import (
    grid_edges,
    random_graph_edges,
    scalefree_edges,
    write_dcop,
)


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "graph_coloring", help="generate a graph-coloring DCOP"
    )
    p.add_argument(
        "--graph", choices=["random", "grid", "scalefree"], default="random"
    )
    p.add_argument("--variables_count", "-n", type=int, required=True)
    p.add_argument("--colors_count", "-c", type=int, default=3)
    p.add_argument(
        "--p_edge", "-p", type=float, default=0.2,
        help="edge probability (random graphs)",
    )
    p.add_argument(
        "--m_edge", "-m", type=int, default=2,
        help="edges per new vertex (scale-free graphs)",
    )
    p.add_argument(
        "--soft", action="store_true",
        help="random violation weights instead of unit penalties",
    )
    p.add_argument(
        "--noise", type=float, default=0.0,
        help="add per-variable noisy value preferences of this level",
    )
    p.add_argument(
        "--intentional", action="store_true",
        help="emit intentional (expression) constraints instead of "
        "extensional cost tables",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--agents_count", type=int, default=None,
        help="also generate this many agents (default: one per variable)",
    )
    p.add_argument("--capacity", type=float, default=100.0)
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    return write_dcop(args, generate(args))


def generate(args):
    import numpy as np

    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import (
        AgentDef,
        Domain,
        Variable,
        VariableNoisyCostFunc,
    )
    from pydcop_tpu.dcop.relations import (
        NAryMatrixRelation,
        relation_from_str,
    )
    from pydcop_tpu.utils.expressionfunction import ExpressionFunction

    rnd = random.Random(args.seed)
    n = args.variables_count
    if args.graph == "random":
        edges = random_graph_edges(rnd, n, args.p_edge)
    elif args.graph == "grid":
        side = int(round(n ** 0.5))
        if side * side != n:
            raise SystemExit(
                f"grid graphs need a square variables_count, got {n}"
            )
        edges = grid_edges(side, side)
    else:
        edges = scalefree_edges(rnd, n, args.m_edge)

    dcop = DCOP(
        f"graph_coloring_{args.graph}_{n}",
        objective="min",
        description=f"soft graph coloring, {len(edges)} edges, "
        f"{args.colors_count} colors, seed {args.seed}",
    )
    colors = Domain("colors", "color", list(range(args.colors_count)))
    variables = []
    for i in range(n):
        if args.noise > 0:
            name = f"v{i:05d}"
            v = VariableNoisyCostFunc(
                name,
                colors,
                ExpressionFunction(f"0 * {name}"),  # pure symmetry noise
                noise_level=args.noise,
            )
        else:
            v = Variable(f"v{i:05d}", colors)
        variables.append(v)
        dcop.add_variable(v)

    d = args.colors_count
    for i, j in edges:
        w = rnd.uniform(0.0, 1.0) if args.soft else 1.0
        vi, vj = variables[i], variables[j]
        name = f"c_{vi.name}_{vj.name}"
        if args.intentional:
            dcop.add_constraint(
                relation_from_str(
                    name,
                    f"{w} if {vi.name} == {vj.name} else 0",
                    [vi, vj],
                )
            )
        else:
            matrix = np.where(np.eye(d, dtype=bool), np.float32(w), 0.0)
            dcop.add_constraint(
                NAryMatrixRelation([vi, vj], matrix, name=name)
            )

    n_agents = args.agents_count if args.agents_count else n
    dcop.add_agents(
        [
            AgentDef(f"a{i:05d}", capacity=args.capacity)
            for i in range(n_agents)
        ]
    )
    return dcop
