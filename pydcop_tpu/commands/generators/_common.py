"""Shared helpers for problem generators."""

from __future__ import annotations

import random
from typing import List, Tuple


def write_dcop(args, dcop) -> int:
    from pydcop_tpu.dcop.yamldcop import dcop_yaml

    text = dcop_yaml(dcop)
    if getattr(args, "output", None):
        with open(args.output, "w") as f:
            f.write(text)
    else:
        print(text)
    return 0


def random_graph_edges(
    rnd: random.Random, n: int, p: float
) -> List[Tuple[int, int]]:
    """Erdős–Rényi G(n, p), forced connected by chaining any isolated
    vertex to a random earlier one (as the reference generator does to
    keep instances solvable/communicating)."""
    edges = set()
    for i in range(n):
        for j in range(i + 1, n):
            if rnd.random() < p:
                edges.add((i, j))
    if n < 2:
        return sorted(edges)  # a single vertex has no edges to force
    degree = [0] * n
    for i, j in edges:
        degree[i] += 1
        degree[j] += 1
    for i in range(n):
        if degree[i] == 0:
            j = rnd.randrange(n - 1)
            if j >= i:
                j += 1
            edges.add((min(i, j), max(i, j)))
            degree[i] += 1
            degree[j] += 1
    return sorted(edges)


def grid_edges(rows: int, cols: int) -> List[Tuple[int, int]]:
    """4-neighborhood grid; vertex id = r * cols + c."""
    edges = []
    for r in range(rows):
        for c in range(cols):
            v = r * cols + c
            if c + 1 < cols:
                edges.append((v, v + 1))
            if r + 1 < rows:
                edges.append((v, v + cols))
    return edges


def scalefree_edges(
    rnd: random.Random, n: int, m: int
) -> List[Tuple[int, int]]:
    """Barabási–Albert preferential attachment with m edges per new
    vertex."""
    if n <= m:
        raise SystemExit(
            f"scale-free graph needs variables_count > m ({n} <= {m})"
        )
    edges = set()
    targets = list(range(m))
    repeated: List[int] = []
    for v in range(m, n):
        for t in targets:
            edges.add((min(v, t), max(v, t)))
        repeated.extend(targets)
        repeated.extend([v] * m)
        # draw until m DISTINCT targets (networkx _random_subset
        # semantics): sampling positions from the multiset can repeat a
        # vertex, which would silently drop edges after dedup
        chosen: List[int] = []
        while len(chosen) < m:
            t = rnd.choice(repeated)
            if t not in chosen:
                chosen.append(t)
        targets = chosen
    return sorted(edges)
