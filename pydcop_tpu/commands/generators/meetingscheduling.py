"""Meeting-scheduling problem generator (PEAV encoding).

Role-equivalent to the reference's ``generators/meetingscheduling.py``:
resources (people) attend events (meetings) scheduled into time slots.
PEAV (Private Events As Variables): each resource owns one variable per
event it attends, whose domain is the slot set.  Constraints:

- equality between all variables of one event (every participant agrees
  on the slot) — violation cost ``--eq_cost``;
- mutual exclusion between variables of the same resource whose events
  would overlap (same slot) — violation cost ``--noconflict_cost``;
- a per-variable preference cost: each resource values each slot
  randomly in ``U(0, value_range)`` (expressed extensionally).
"""

from __future__ import annotations

import random

import numpy as np

from pydcop_tpu.commands.generators._common import write_dcop


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "meeting_scheduling",
        help="generate a PEAV meeting-scheduling DCOP",
    )
    p.add_argument("--slots_count", "-s", type=int, required=True)
    p.add_argument("--events_count", "-e", type=int, required=True)
    p.add_argument("--resources_count", "-r", type=int, required=True)
    p.add_argument(
        "--max_resources_event", type=int, default=2,
        help="resources drawn per event (attendance)",
    )
    p.add_argument("--eq_cost", type=float, default=10.0)
    p.add_argument("--noconflict_cost", type=float, default=10.0)
    p.add_argument("--value_range", type=float, default=1.0)
    p.add_argument("--capacity", type=float, default=100.0)
    p.add_argument("--seed", type=int, default=0)
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    return write_dcop(args, generate(args))


def generate(args):
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rnd = random.Random(args.seed)
    n_slots = args.slots_count

    dcop = DCOP(
        f"meetings_{args.events_count}e_{args.resources_count}r_{n_slots}s",
        objective="min",
        description="PEAV meeting scheduling, seed %d" % args.seed,
    )
    slots = Domain("slots", "time_slot", list(range(n_slots)))

    # attendance: each event draws its participants
    attendance = {}
    for e in range(args.events_count):
        k = min(
            args.max_resources_event, args.resources_count
        )
        attendance[e] = sorted(
            rnd.sample(range(args.resources_count), k)
        )

    # PEAV variables: one per (resource, attended event)
    variables = {}
    for e, members in attendance.items():
        for r in members:
            v = Variable(f"m{e:03d}_r{r:03d}", slots)
            variables[(e, r)] = v
            dcop.add_variable(v)

    eye = np.eye(n_slots, dtype=bool)
    eq_matrix = np.where(eye, 0.0, np.float32(args.eq_cost))
    excl_matrix = np.where(eye, np.float32(args.noconflict_cost), 0.0)

    # equality inside one event: ALL pairs of participant variables
    # (PEAV encoding), not a chain — the chain under-penalizes
    # disagreement for events with >2 participants
    import itertools

    for e, members in attendance.items():
        for r1, r2 in itertools.combinations(members, 2):
            v1 = variables[(e, r1)]
            v2 = variables[(e, r2)]
            dcop.add_constraint(
                NAryMatrixRelation(
                    [v1, v2], eq_matrix, name=f"eq_{v1.name}_{v2.name}"
                )
            )

    # mutual exclusion inside one resource's calendar
    by_resource = {}
    for (e, r), v in variables.items():
        by_resource.setdefault(r, []).append(v)
    for r, vs in by_resource.items():
        for i in range(len(vs)):
            for j in range(i + 1, len(vs)):
                dcop.add_constraint(
                    NAryMatrixRelation(
                        [vs[i], vs[j]],
                        excl_matrix,
                        name=f"excl_{vs[i].name}_{vs[j].name}",
                    )
                )

    # slot preferences per (resource, event) variable
    for (e, r), v in variables.items():
        prefs = np.array(
            [rnd.uniform(0, args.value_range) for _ in range(n_slots)],
            dtype=np.float32,
        )
        dcop.add_constraint(
            NAryMatrixRelation([v], prefs, name=f"pref_{v.name}")
        )

    # one agent per resource (it owns that resource's variables)
    dcop.add_agents(
        [
            AgentDef(f"a{r:03d}", capacity=args.capacity)
            for r in range(args.resources_count)
        ]
    )
    dcop.dist_hints = _hints(by_resource)
    return dcop


def _hints(by_resource):
    from pydcop_tpu.distribution.objects import DistributionHints

    return DistributionHints(
        must_host={
            f"a{r:03d}": [v.name for v in vs]
            for r, vs in by_resource.items()
        }
    )
