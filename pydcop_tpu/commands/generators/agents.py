"""Agents-file generator.

Role-equivalent to the reference's ``generators/agents.py``: emit a
standalone yaml ``agents:`` section (agent definitions with capacity
and optional random hosting/route costs) to combine with a separately
generated problem file.
"""

from __future__ import annotations

import random

import yaml


def set_parser(subparsers) -> None:
    p = subparsers.add_parser("agents", help="generate an agents yaml")
    p.add_argument("--count", "-n", type=int, required=True)
    p.add_argument("--capacity", type=float, default=100.0)
    p.add_argument(
        "--hosting_default", type=float, default=None,
        help="default hosting cost (omitted if not set)",
    )
    p.add_argument(
        "--routes_default", type=float, default=None,
        help="default route cost (omitted if not set)",
    )
    p.add_argument(
        "--hosting_range", type=float, default=0.0,
        help="draw default hosting costs from U(0, range) per agent",
    )
    p.add_argument("--agent_prefix", default="a")
    p.add_argument("--seed", type=int, default=0)
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    rnd = random.Random(args.seed)
    width = len(str(max(args.count - 1, 1)))
    agents = {}
    for i in range(args.count):
        ad = {"capacity": args.capacity}
        hosting = args.hosting_default
        if args.hosting_range:
            hosting = round(rnd.uniform(0, args.hosting_range), 3)
        if hosting is not None:
            ad["hosting"] = {"default": hosting}
        if args.routes_default is not None:
            ad["routes"] = {"default": args.routes_default}
        agents[f"{args.agent_prefix}{i:0{width}d}"] = ad
    text = yaml.safe_dump({"agents": agents}, sort_keys=False)
    if getattr(args, "output", None):
        with open(args.output, "w") as f:
            f.write(text)
    else:
        print(text)
    return 0
