"""Forbidden-pair task-scheduling generator — the sparse-table
workload (``docs/performance.md``, "Sparse constraint tables").

``nb_tasks`` jobs each pick one of ``nb_slots`` time slots.  A chain
of sliding windows of ``--window`` consecutive tasks (advancing by
``--stride``, like the SECP overlap layout) carries one extensional
constraint per window: every pair of tasks inside the window draws a
random set of FORBIDDEN slot pairs with density ``--forbid_density``
(machine conflicts, crew exclusions, setup incompatibilities — the
hard-cap analogue of ``secp --hard_cap``), and a joint tuple whose
ANY pair lands in a forbidden set costs ``+inf``.  Feasible tuples
pay the soft lateness ``sum_t |slot_t - due_t|``, with due dates
drawn from a PLANTED schedule whose pairs are never forbidden — so
every instance is feasible by construction and the planted schedule
costs 0.

Sparsity is the point: a window of arity ``k`` survives all its
``k·(k-1)/2`` pairwise filters with probability about
``(1 - forbid_density)^(k(k-1)/2)`` per tuple — the defaults
(``window=4``, ``forbid_density=0.5``) leave ~1.6% of cells finite,
i.e. >= 98% ``+inf``.  Dense UTIL packs must ship (and a
``--max_util_bytes`` planner must budget) the full ``d^k`` box
regardless; ``--table_format sparse`` packs only the feasible tuples
(``ops/sparse.py``), so the same byte budget holds windows no dense
plan could fit (``tests/test_generators.py``).
"""

from __future__ import annotations

import itertools
import random

import numpy as np

from pydcop_tpu.commands.generators._common import write_dcop


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "task_scheduling",
        help="generate a forbidden-pair task-scheduling DCOP "
        "(>=90%%-infeasible windowed tables — the sparse "
        "table_format workload)",
    )
    p.add_argument("--nb_tasks", type=int, required=True)
    p.add_argument(
        "--nb_slots", type=int, default=8,
        help="time slots per task (the domain size)",
    )
    p.add_argument(
        "--window", type=int, default=4,
        help="tasks per sliding-window constraint (the table "
        "arity): cell count d^window, so window x nb_slots sets "
        "the dense table size the sparse pack undercuts",
    )
    p.add_argument(
        "--stride", type=int, default=2,
        help="window advance; stride < window chains consecutive "
        "windows through shared tasks (wider separators, deeper "
        "pseudo-tree) exactly like secp --zone_layout overlap",
    )
    p.add_argument(
        "--forbid_density", type=float, default=0.5,
        help="probability each (task pair, slot pair) is forbidden "
        "(+inf).  A window of arity k keeps a tuple with "
        "probability ~(1-p)^(k(k-1)/2): the default 0.5 at "
        "window=4 leaves ~1.6%% of cells finite.  The planted "
        "schedule's pairs are never forbidden, so instances stay "
        "feasible at any density < 1",
    )
    p.add_argument(
        "--lateness_weight", type=float, default=1.0,
        help="soft cost per slot of |slot - due| lateness on "
        "feasible tuples (due dates = the planted schedule)",
    )
    p.add_argument("--capacity", type=float, default=100.0)
    p.add_argument("--seed", type=int, default=0)
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    return write_dcop(args, generate(args))


def generate(args):
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    n, d = int(args.nb_tasks), int(args.nb_slots)
    k = int(args.window)
    stride = int(args.stride)
    p_forbid = float(args.forbid_density)
    if not 2 <= k <= n:
        raise ValueError(
            f"window={k} must be in [2, nb_tasks={n}] — a window "
            "of one task has no pairs to forbid"
        )
    if not 0 < stride <= k:
        raise ValueError(
            f"stride={stride} must be in [1, window={k}] — a "
            "stride past the window leaves tasks constraint-free"
        )
    if not 0.0 <= p_forbid < 1.0:
        raise ValueError(
            f"forbid_density={p_forbid} must be in [0, 1) — at 1 "
            "every non-planted pair is outlawed and the table "
            "degenerates to the single planted tuple"
        )

    rnd = random.Random(args.seed)
    dcop = DCOP(
        f"tasks_{n}t_{d}s_w{k}",
        objective="min",
        description=(
            "forbidden-pair task scheduling, seed %d" % args.seed
        ),
    )
    slots = Domain("slots", "time_slot", list(range(d)))
    tasks = []
    for i in range(n):
        v = Variable(f"t{i:04d}", slots)
        tasks.append(v)
        dcop.add_variable(v)

    # the planted schedule: due dates AND the feasibility witness
    planted = [rnd.randrange(d) for _ in range(n)]

    # forbidden slot pairs per ORDERED task pair (i < j), drawn once
    # globally so overlapping windows agree on the shared pairs'
    # conflicts — two windows disagreeing about the same task pair
    # would encode no consistent scheduling story
    forbid: dict = {}

    def _pairs(i: int, j: int) -> np.ndarray:
        key = (i, j)
        m = forbid.get(key)
        if m is None:
            m = np.zeros((d, d), dtype=bool)
            for a in range(d):
                for b in range(d):
                    if rnd.random() < p_forbid:
                        m[a, b] = True
            m[planted[i], planted[j]] = False
            forbid[key] = m
        return m

    w = float(args.lateness_weight)
    anchors = list(range(0, max(n - k, 0) + 1, stride))
    if anchors[-1] != n - k:
        anchors.append(n - k)  # the tail window covers the last tasks
    for a in anchors:
        scope_ids = list(range(a, a + k))
        shape = (d,) * k
        matrix = np.zeros(shape, dtype=np.float64)
        pair_masks = [
            (x, y, _pairs(scope_ids[x], scope_ids[y]))
            for x, y in itertools.combinations(range(k), 2)
        ]
        for idx in itertools.product(range(d), repeat=k):
            if any(m[idx[x], idx[y]] for x, y, m in pair_masks):
                matrix[idx] = np.inf
            else:
                matrix[idx] = w * sum(
                    abs(idx[x] - planted[t])
                    for x, t in enumerate(scope_ids)
                )
        dcop.add_constraint(
            NAryMatrixRelation(
                [tasks[t] for t in scope_ids], matrix,
                name=f"win{a:04d}",
            )
        )

    dcop.add_agents(
        [
            AgentDef(f"a{i:04d}", capacity=args.capacity)
            for i in range(n)
        ]
    )
    return dcop
