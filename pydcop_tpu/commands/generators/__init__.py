"""Benchmark-problem generators (reference: ``pydcop/commands/generators/``).

Each module exports ``set_parser(subparsers)`` registering one
``pydcop_tpu generate <kind>`` sub-subcommand whose handler builds a
:class:`~pydcop_tpu.dcop.dcop.DCOP` (or an agents yaml) and writes it
to stdout / ``--output``.
"""

GENERATORS = [
    "graphcoloring",
    "ising",
    "meetingscheduling",
    "secp",
    "taskscheduling",
    "agents",
]
