"""SECP (Smart Environment Configuration Problem) generator.

Role-equivalent to the reference's ``generators/secp.py`` /
``generators/iot.py``: the smart-lighting scenario from the SECP papers.
Lights are dimmable actuators (variables, levels 0..k); *models* are
target light levels for zones, expressed as n-ary constraints over the
lights reaching the zone (cost = |weighted level sum − target|); *rules*
are scene preferences pinning a light near a level (unary); each light
also pays an efficiency cost proportional to its level.
"""

from __future__ import annotations

import itertools
import random

import numpy as np

from pydcop_tpu.commands.generators._common import write_dcop


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "secp", help="generate a smart-lighting SECP DCOP"
    )
    p.add_argument("--nb_lights", "-l", type=int, required=True)
    p.add_argument("--nb_models", "-m", type=int, required=True)
    p.add_argument("--nb_rules", "-r", type=int, required=True)
    p.add_argument(
        "--light_levels", type=int, default=5,
        help="dimmer resolution (domain size)",
    )
    p.add_argument(
        "--model_arity", type=int, default=3,
        help="max lights per model zone",
    )
    p.add_argument(
        "--zone_size", type=int, default=0,
        help="locality window: each model draws its lights from a "
        "window of this many consecutive lights (0 = anywhere). "
        "Bounds the constraint graph's treewidth the way physical "
        "rooms do — required for exact DPOP at scale",
    )
    p.add_argument(
        "--zone_layout", choices=["random", "tiled", "overlap"],
        default="random",
        help="'random': zone windows start anywhere (overlapping "
        "windows chain the whole building into one deep band); "
        "'tiled': windows align to disjoint zone_size blocks — "
        "independent rooms, giving the wide shallow pseudo-forest "
        "that DPOP's level-synchronous UTIL batching exploits "
        "(docs/performance.md, 'Level-synchronous DPOP'); "
        "'overlap': windows slide by zone_size - zone_overlap so "
        "every consecutive pair of zones SHARES zone_overlap lights "
        "— an open-plan floor whose chained zones drive the induced "
        "width up with the overlap degree, the workload the "
        "memory-bounded planner (--max_util_bytes, "
        "docs/semirings.md) exists for; tiled zones are deliberately "
        "shallow and can never exercise it",
    )
    p.add_argument(
        "--zone_overlap", type=int, default=0,
        help="(zone_layout=overlap) lights shared by consecutive "
        "zone windows (0 = half the zone).  More overlap = wider "
        "separators = exponentially bigger UTIL tables",
    )
    p.add_argument(
        "--efficiency_weight", type=float, default=0.1,
        help="unary cost per emitted light level",
    )
    p.add_argument(
        "--hard_cap", type=float, default=0.0,
        help="over-illumination HARD cap: a model window whose "
        "level sum exceeds hard_cap x its target costs +inf "
        "(infeasible), not just |sum - target| — the power-budget "
        "rule of real lighting deployments.  Must be > 1 when set "
        "(0 = off, all-soft costs).  Hard caps give the "
        "branch-and-bound pruned kernels (--bnb, docs/semirings.md "
        "'Branch-and-bound pruning') their bite: jointly-infeasible "
        "and provably-over-budget separator rows prune in-kernel, "
        "which single-part consistency pruning "
        "(ops/membound.py:prune_plan) cannot see",
    )
    p.add_argument("--capacity", type=float, default=100.0)
    p.add_argument("--seed", type=int, default=0)
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    return write_dcop(args, generate(args))


def generate(args):
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rnd = random.Random(args.seed)
    levels = args.light_levels

    dcop = DCOP(
        f"secp_{args.nb_lights}l_{args.nb_models}m_{args.nb_rules}r",
        objective="min",
        description="SECP smart lighting, seed %d" % args.seed,
    )
    lum = Domain("lum", "luminosity", list(range(levels)))

    lights = []
    for i in range(args.nb_lights):
        v = Variable(f"l{i:04d}", lum)
        lights.append(v)
        dcop.add_variable(v)
        # efficiency: cost grows with emitted level
        cost = np.arange(levels, dtype=np.float32) * args.efficiency_weight
        dcop.add_constraint(
            NAryMatrixRelation([v], cost, name=f"eff_{v.name}")
        )

    max_level = levels - 1
    zone = int(getattr(args, "zone_size", 0) or 0)
    hard_cap = float(getattr(args, "hard_cap", 0.0) or 0.0)
    if hard_cap and hard_cap <= 1.0:
        raise ValueError(
            f"hard_cap={hard_cap} must be > 1 (it multiplies the "
            "model target; at <= 1 the cap would outlaw the target "
            "itself)"
        )
    for m in range(args.nb_models):
        arity = rnd.randint(1, min(args.model_arity, args.nb_lights))
        if zone and zone < args.nb_lights:
            layout = getattr(args, "zone_layout", "random")
            if layout == "tiled":
                # disjoint rooms: windows snap to zone_size blocks;
                # ceil so a non-divisible nb_lights puts the tail
                # lights in a final short room instead of leaving
                # them model-free
                n_blocks = -(-args.nb_lights // zone)
                start = rnd.randrange(n_blocks) * zone
            elif layout == "overlap":
                # chained zones: window m slides by stride =
                # zone - overlap, so consecutive zones share exactly
                # `overlap` lights — every shared light sits in two
                # zones' separators and the chain's induced width
                # grows with the overlap degree (deterministic
                # anchors per model index; the scope draw below
                # stays seeded-random inside the window).  Anchors
                # CYCLE over the fixed lattice 0, stride, 2·stride…
                # instead of wrapping mid-stride: model counts past
                # one full sweep of the strip revisit the SAME
                # chain of windows (a raw (m·stride) % span would
                # drift the anchors by a few lights per wrap and
                # consecutive zones at the seam would share
                # nothing).
                overlap = int(
                    getattr(args, "zone_overlap", 0) or 0
                ) or zone // 2
                if not 0 < overlap < zone:
                    raise ValueError(
                        f"zone_overlap={overlap} must be in "
                        f"[1, zone_size={zone}) — equal windows "
                        "never advance, and a non-positive overlap "
                        "is not an overlap"
                    )
                stride = zone - overlap
                span = args.nb_lights - zone + 1
                n_anchors = (span - 1) // stride + 1
                start = (m % n_anchors) * stride
            else:
                start = rnd.randrange(args.nb_lights - zone + 1)
            pool = lights[start : start + zone]
            scope = rnd.sample(pool, min(arity, len(pool)))
        else:
            scope = rnd.sample(lights, arity)
        target = rnd.uniform(0.3, 1.0) * arity * max_level
        shape = (levels,) * arity
        matrix = np.zeros(shape, dtype=np.float32)
        for idx in itertools.product(range(levels), repeat=arity):
            s = sum(idx)
            if hard_cap and s > hard_cap * target:
                matrix[idx] = np.inf
            else:
                matrix[idx] = abs(s - target)
        dcop.add_constraint(
            NAryMatrixRelation(scope, matrix, name=f"mod{m:03d}")
        )

    for r in range(args.nb_rules):
        light = rnd.choice(lights)
        wanted = rnd.randrange(levels)
        cost = np.abs(
            np.arange(levels, dtype=np.float32) - wanted
        )
        dcop.add_constraint(
            NAryMatrixRelation([light], cost, name=f"rule{r:03d}")
        )

    # one agent per light, as in the IoT deployment story
    dcop.add_agents(
        [
            AgentDef(
                f"a{i:04d}",
                capacity=args.capacity,
                default_hosting_cost=10.0,
                hosting_costs={lights[i].name: 0.0},
            )
            for i in range(args.nb_lights)
        ]
    )
    return dcop
