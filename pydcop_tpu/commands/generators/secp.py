"""SECP (Smart Environment Configuration Problem) generator.

Role-equivalent to the reference's ``generators/secp.py`` /
``generators/iot.py``: the smart-lighting scenario from the SECP papers.
Lights are dimmable actuators (variables, levels 0..k); *models* are
target light levels for zones, expressed as n-ary constraints over the
lights reaching the zone (cost = |weighted level sum − target|); *rules*
are scene preferences pinning a light near a level (unary); each light
also pays an efficiency cost proportional to its level.
"""

from __future__ import annotations

import itertools
import random

import numpy as np

from pydcop_tpu.commands.generators._common import write_dcop


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "secp", help="generate a smart-lighting SECP DCOP"
    )
    p.add_argument("--nb_lights", "-l", type=int, required=True)
    p.add_argument("--nb_models", "-m", type=int, required=True)
    p.add_argument("--nb_rules", "-r", type=int, required=True)
    p.add_argument(
        "--light_levels", type=int, default=5,
        help="dimmer resolution (domain size)",
    )
    p.add_argument(
        "--model_arity", type=int, default=3,
        help="max lights per model zone",
    )
    p.add_argument(
        "--zone_size", type=int, default=0,
        help="locality window: each model draws its lights from a "
        "window of this many consecutive lights (0 = anywhere). "
        "Bounds the constraint graph's treewidth the way physical "
        "rooms do — required for exact DPOP at scale",
    )
    p.add_argument(
        "--zone_layout", choices=["random", "tiled"],
        default="random",
        help="'random': zone windows start anywhere (overlapping "
        "windows chain the whole building into one deep band); "
        "'tiled': windows align to disjoint zone_size blocks — "
        "independent rooms, giving the wide shallow pseudo-forest "
        "that DPOP's level-synchronous UTIL batching exploits "
        "(docs/performance.md, 'Level-synchronous DPOP')",
    )
    p.add_argument(
        "--efficiency_weight", type=float, default=0.1,
        help="unary cost per emitted light level",
    )
    p.add_argument("--capacity", type=float, default=100.0)
    p.add_argument("--seed", type=int, default=0)
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    return write_dcop(args, generate(args))


def generate(args):
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rnd = random.Random(args.seed)
    levels = args.light_levels

    dcop = DCOP(
        f"secp_{args.nb_lights}l_{args.nb_models}m_{args.nb_rules}r",
        objective="min",
        description="SECP smart lighting, seed %d" % args.seed,
    )
    lum = Domain("lum", "luminosity", list(range(levels)))

    lights = []
    for i in range(args.nb_lights):
        v = Variable(f"l{i:04d}", lum)
        lights.append(v)
        dcop.add_variable(v)
        # efficiency: cost grows with emitted level
        cost = np.arange(levels, dtype=np.float32) * args.efficiency_weight
        dcop.add_constraint(
            NAryMatrixRelation([v], cost, name=f"eff_{v.name}")
        )

    max_level = levels - 1
    zone = int(getattr(args, "zone_size", 0) or 0)
    for m in range(args.nb_models):
        arity = rnd.randint(1, min(args.model_arity, args.nb_lights))
        if zone and zone < args.nb_lights:
            if getattr(args, "zone_layout", "random") == "tiled":
                # disjoint rooms: windows snap to zone_size blocks;
                # ceil so a non-divisible nb_lights puts the tail
                # lights in a final short room instead of leaving
                # them model-free
                n_blocks = -(-args.nb_lights // zone)
                start = rnd.randrange(n_blocks) * zone
            else:
                start = rnd.randrange(args.nb_lights - zone + 1)
            pool = lights[start : start + zone]
            scope = rnd.sample(pool, min(arity, len(pool)))
        else:
            scope = rnd.sample(lights, arity)
        target = rnd.uniform(0.3, 1.0) * arity * max_level
        shape = (levels,) * arity
        matrix = np.zeros(shape, dtype=np.float32)
        for idx in itertools.product(range(levels), repeat=arity):
            matrix[idx] = abs(sum(idx) - target)
        dcop.add_constraint(
            NAryMatrixRelation(scope, matrix, name=f"mod{m:03d}")
        )

    for r in range(args.nb_rules):
        light = rnd.choice(lights)
        wanted = rnd.randrange(levels)
        cost = np.abs(
            np.arange(levels, dtype=np.float32) - wanted
        )
        dcop.add_constraint(
            NAryMatrixRelation([light], cost, name=f"rule{r:03d}")
        )

    # one agent per light, as in the IoT deployment story
    dcop.add_agents(
        [
            AgentDef(
                f"a{i:04d}",
                capacity=args.capacity,
                default_hosting_cost=10.0,
                hosting_costs={lights[i].name: 0.0},
            )
            for i in range(args.nb_lights)
        ]
    )
    return dcop
