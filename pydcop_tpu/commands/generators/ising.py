"""Ising-model problem generator.

Role-equivalent to the reference's ``generators/ising.py``: a
``row_count × col_count`` torus of binary spins; each edge carries a
coupling sampled from ``U(-k, k)`` (cost ``J`` when spins agree, ``-J``
when they differ) and each spin a random external field from
``U(-r, r)`` expressed as a unary extensional constraint.
"""

from __future__ import annotations

import random

import numpy as np

from pydcop_tpu.commands.generators._common import write_dcop


def set_parser(subparsers) -> None:
    p = subparsers.add_parser("ising", help="generate an Ising-grid DCOP")
    p.add_argument("--row_count", type=int, required=True)
    p.add_argument("--col_count", type=int, default=None)
    p.add_argument(
        "--bin_range", "-k", type=float, default=1.6,
        help="coupling strengths drawn from U(-k, k)",
    )
    p.add_argument(
        "--un_range", "-r", type=float, default=0.05,
        help="external fields drawn from U(-r, r)",
    )
    p.add_argument(
        "--no_agents", action="store_true",
        help="do not generate agent definitions",
    )
    p.add_argument("--capacity", type=float, default=100.0)
    p.add_argument("--seed", type=int, default=0)
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    return write_dcop(args, generate(args))


def generate(args):
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rows = args.row_count
    cols = args.col_count or rows
    rnd = random.Random(args.seed)

    dcop = DCOP(
        f"ising_{rows}x{cols}",
        objective="min",
        description=f"Ising torus {rows}x{cols}, couplings U(±{args.bin_range}),"
        f" fields U(±{args.un_range}), seed {args.seed}",
    )
    spin = Domain("spin", "binary", [0, 1])

    grid = {}
    for r in range(rows):
        for c in range(cols):
            v = Variable(f"v_{r}_{c}", spin)
            grid[(r, c)] = v
            dcop.add_variable(v)

    # torus edges: right and down neighbors (wrapping); on grids of
    # width/height <= 2 the wrap revisits pairs, so dedupe on the
    # canonical (sorted) pair
    seen = set()
    for r in range(rows):
        for c in range(cols):
            v = grid[(r, c)]
            for dr, dc in ((0, 1), (1, 0)):
                r2, c2 = (r + dr) % rows, (c + dc) % cols
                if (r2, c2) == (r, c):
                    continue  # degenerate 1-wide torus
                u = grid[(r2, c2)]
                pair = tuple(sorted((v.name, u.name)))
                if pair in seen:
                    continue
                seen.add(pair)
                coupling = rnd.uniform(-args.bin_range, args.bin_range)
                matrix = np.array(
                    [[coupling, -coupling], [-coupling, coupling]],
                    dtype=np.float32,
                )
                dcop.add_constraint(
                    NAryMatrixRelation(
                        [v, u], matrix, name=f"c_{pair[0]}_{pair[1]}"
                    )
                )

    for v in grid.values():
        field = rnd.uniform(-args.un_range, args.un_range)
        matrix = np.array([field, -field], dtype=np.float32)
        dcop.add_constraint(
            NAryMatrixRelation([v], matrix, name=f"u_{v.name}")
        )

    if not args.no_agents:
        dcop.add_agents(
            [
                AgentDef(f"a_{r}_{c}", capacity=args.capacity)
                for r in range(rows)
                for c in range(cols)
            ]
        )
    return dcop
