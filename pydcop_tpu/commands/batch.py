"""``pydcop_tpu batch`` — placeholder, implemented in a later milestone
(reference: ``pydcop/commands/batch.py``)."""


def set_parser(subparsers) -> None:
    p = subparsers.add_parser("batch", help="(not yet implemented)")
    p.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    raise SystemExit("batch: not yet implemented in this build")
