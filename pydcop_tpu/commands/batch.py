"""``pydcop_tpu batch`` (reference: ``pydcop/commands/batch.py``).

Parameter-sweep experiment runner: a yaml spec defines problem *sets*
(file globs + iteration counts) and *batches* (command options, with
list-valued parameters expanded as a cross product).  Every (batch,
problem, parameter-combination, iteration) tuple is solved in-process
on the batched engine and appended as one CSV row — the reference's
reproducibility harness.

Finished runs are skipped when the output CSV already contains their
key, giving crude experiment-level resume (same behavior the reference
gets by skipping existing output files).

``--vmap_iterations`` collapses each (problem, parameter-combination)
cell's iterations into ONE vmapped multi-restart solve (engine
``n_restarts``) — the TPU-idiomatic way to run repetition sweeps: K
iterations at roughly one run's wall-clock, one row per iteration from
the per-restart cost distribution.

Spec format::

    sets:
      coloring:
        path: "instances/coloring_*.yaml"   # glob or list of files
        iterations: 3                        # seeds 0..2
    batches:
      dsa_sweep:
        algo: dsa
        algo_params:
          variant: [A, B, C]                 # lists are swept
          probability: 0.7
        rounds: 200
        timeout: 10
"""

from __future__ import annotations

import csv
import glob as globmod
import itertools
import json
import os
from typing import Any, Dict, Iterator, List, Tuple

# batch options forwarded to api.solve (anything else is a spec error)
_SOLVE_OPTIONS = {
    "rounds",
    "timeout",
    "chunk_size",
    "convergence_chunks",
    "n_restarts",
    "pad_policy",
    # supervised device dispatch (engine/supervisor.py): a sweep can
    # tune retry/degradation policy per batch — useful on busy shared
    # accelerators where transient failures and HBM pressure are real
    "retry_budget",
    "chunk_floor",
    "on_numeric_fault",
}


def _supervisor_options(options: Dict[str, Any]) -> Dict[str, Any]:
    """The supervised-dispatch knobs of a batch spec, typed for
    ``api.solve``/``api.solve_many`` (absent keys stay None = the
    supervisor defaults)."""
    out: Dict[str, Any] = {}
    if options.get("retry_budget") is not None:
        out["retry_budget"] = int(options["retry_budget"])
    if options.get("chunk_floor") is not None:
        out["chunk_floor"] = int(options["chunk_floor"])
    if options.get("on_numeric_fault") is not None:
        out["on_numeric_fault"] = str(options["on_numeric_fault"])
    return out

CSV_FIELDS = [
    "batch",
    "set",
    "problem",
    "iteration",
    "algo",
    "params",
    "status",
    "cost",
    "cycle",
    "msg_count",
    "time",
]


def set_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "batch", help="run a parameter-sweep experiment from a yaml spec"
    )
    p.add_argument("spec", help="batch spec yaml file")
    p.add_argument(
        "--result_file", default="batch_results.csv",
        help="CSV to append per-run rows to (existing rows are skipped)",
    )
    p.add_argument(
        "--simulate", action="store_true",
        help="list the runs without executing them",
    )
    p.add_argument(
        "--vmap_iterations", action="store_true",
        help="solve all iterations of a (problem, params) cell as ONE "
        "vmapped multi-restart run (engine n_restarts) — K iterations "
        "at roughly one run's wall-clock on accelerators.  Each "
        "iteration's row gets its own restart's cost; RNG streams "
        "differ from sequential per-seed runs (both are valid "
        "independent samples, but rows are not bit-reproducible "
        "across the two modes).  Applies only to plain fixed-round "
        "cells; cells with timeout/convergence_chunks (early stops "
        "would truncate non-best restarts), partially-done cells, "
        "host-path algorithms, single-iteration cells, and cells "
        "whose vmapped solve fails all fall back to sequential runs",
    )
    p.add_argument(
        "--vmap_cells", action="store_true",
        help="collapse WHOLE same-bucket groups of (problem x params "
        "x iteration) cells into one vmapped device call each "
        "(api.solve_many): every pending run becomes one instance "
        "with seed=iteration, instances whose compiled problems share "
        "a shape bucket (spec option pad_policy, default pow2 here) "
        "and static params solve in one XLA program.  Rows are "
        "bit-identical to sequential runs for deterministic "
        "algorithms.  Cells with timeout/convergence_chunks (early "
        "stops act on a whole group at once) and host-path "
        "algorithms fall back to sequential runs; supersedes "
        "--vmap_iterations for the runs it covers",
    )
    p.set_defaults(func=run_cmd)


def _expand_params(algo_params: Dict[str, Any]) -> Iterator[Dict[str, Any]]:
    """Cross product over list-valued parameters."""
    if not algo_params:
        yield {}
        return
    keys = sorted(algo_params)
    pools = [
        v if isinstance(v, list) else [v]
        for v in (algo_params[k] for k in keys)
    ]
    for combo in itertools.product(*pools):
        yield dict(zip(keys, combo))


def _set_files(set_def: Dict[str, Any], base_dir: str) -> List[str]:
    path = set_def.get("path")
    if isinstance(path, list):
        files: List[str] = []
        for p in path:
            files.extend(_resolve(p, base_dir))
        return files
    return _resolve(path, base_dir)


def _resolve(pattern: str, base_dir: str) -> List[str]:
    if not os.path.isabs(pattern):
        pattern = os.path.join(base_dir, pattern)
    matches = sorted(globmod.glob(pattern))
    return matches if matches else [pattern]


def iter_runs(
    spec: Dict[str, Any], base_dir: str
) -> Iterator[Tuple[str, str, str, int, str, Dict[str, Any], Dict[str, Any]]]:
    """Yield (batch, set, problem, iteration, algo, params, options)."""
    sets = spec.get("sets", {}) or {}
    batches = spec.get("batches", {}) or {}
    for bname, bdef in sorted(batches.items()):
        algo = bdef.get("algo")
        if not algo:
            raise SystemExit(f"batch {bname!r}: missing 'algo'")
        options = {
            k: v
            for k, v in bdef.items()
            if k not in ("algo", "algo_params")
        }
        unknown = set(options) - _SOLVE_OPTIONS
        if unknown:
            raise SystemExit(
                f"batch {bname!r}: unknown option(s) {sorted(unknown)}; "
                f"accepted: {sorted(_SOLVE_OPTIONS)}"
            )
        for sname, sdef in sorted(sets.items()):
            iterations = int(sdef.get("iterations", 1))
            for problem in _set_files(sdef, base_dir):
                for params in _expand_params(bdef.get("algo_params")):
                    for it in range(iterations):
                        yield (
                            bname, sname, problem, it, algo, params, options
                        )


def _run_key(batch, set_, problem, iteration, algo, params, base_dir) -> Tuple:
    # path relative to the spec dir: distinguishes same-named files in
    # different directories, stays stable if the tree moves
    try:
        pkey = os.path.relpath(problem, base_dir)
    except ValueError:  # different drive (windows)
        pkey = problem
    return (
        batch,
        set_,
        pkey,
        str(iteration),
        algo,
        json.dumps(params, sort_keys=True),
    )


def _write_row(writer, run, result, base_dir) -> None:
    batch, set_, problem, it, algo, params, _ = run
    key = _run_key(batch, set_, problem, it, algo, params, base_dir)
    writer.writerow(
        {
            "batch": key[0],
            "set": key[1],
            "problem": key[2],
            "iteration": key[3],
            "algo": key[4],
            "params": key[5],
            "status": result["status"],
            "cost": result["cost"],
            "cycle": result["cycle"],
            "msg_count": result["msg_count"],
            "time": result["time"],
        }
    )


def _vmappable(algo: str) -> bool:
    from pydcop_tpu.algorithms import load_algorithm_module

    try:
        return not hasattr(load_algorithm_module(algo), "solve_host")
    except Exception:
        return False


def _vmap_cells_pass(writer, fobj, runs, done, base_dir):
    """``--vmap_cells``: execute every eligible pending run through
    :func:`pydcop_tpu.api.solve_many`, grouped per batch (options are
    uniform within a batch, so rounds/chunk_size agree).

    Each run becomes one problem instance with ``seed=iteration`` —
    the exact seed the sequential loop would use, so rows are
    bit-identical to sequential execution for deterministic
    algorithms.  ``solve_many`` splits each batch's instances into
    same-bucket, same-static-params groups internally and solves each
    group in one vmapped device program; a batch whose batched solve
    fails falls back (untouched) to the sequential loop.

    Eligible: vmappable (non-host-path) algorithm, no ``timeout`` and
    no ``convergence_chunks`` in the batch options — early stops act
    on a whole fused group at once, which would diverge from the
    per-run semantics the rows claim.

    Returns ``(handled_keys, executed, failed)``.
    """
    from pydcop_tpu.api import solve_many

    handled = set()
    executed = failed = 0
    by_batch: Dict[str, List[Tuple[Tuple, Tuple]]] = {}
    for run in runs:
        batch, set_, problem, it, algo, params, options = run
        key = _run_key(batch, set_, problem, it, algo, params, base_dir)
        if key in done:
            continue
        if options.get("timeout") is not None:
            continue
        if int(options.get("convergence_chunks", 0)):
            continue
        if not _vmappable(algo):
            continue
        by_batch.setdefault(batch, []).append((run, key))
    for batch, pairs in sorted(by_batch.items()):
        _, _, _, _, algo, _, options = pairs[0][0]
        try:
            results = solve_many(
                [run[2] for run, _ in pairs],
                algo,
                [run[5] for run, _ in pairs],
                rounds=int(options.get("rounds", 200)),
                chunk_size=int(options.get("chunk_size", 64)),
                n_restarts=int(options.get("n_restarts", 1)),
                pad_policy=options.get("pad_policy", "pow2"),
                seed=[run[3] for run, _ in pairs],
                **_supervisor_options(options),
            )
        except Exception:
            # e.g. the stacked state OOMs where single runs fit — the
            # whole batch falls back to the sequential per-run loop
            continue
        for (run, key), result in zip(pairs, results):
            # result["time"] is already the instance's even share of
            # its group's wall-clock (api.solve_many)
            _write_row(writer, run, {
                "status": result["status"],
                "cost": result["cost"],
                "cycle": result["cycle"],
                "msg_count": result["msg_count"],
                "time": round(result["time"], 6),
            }, base_dir)
            handled.add(key)
            executed += 1
        fobj.flush()
    return handled, executed, failed


def run_cmd(args) -> int:
    import yaml

    with open(args.spec) as f:
        spec = yaml.safe_load(f)
    base_dir = os.path.dirname(os.path.abspath(args.spec))

    done = set()
    exists = os.path.exists(args.result_file)
    if exists:
        kept_rows: List[Dict[str, str]] = []
        n_errors = 0
        with open(args.result_file, newline="") as f:
            for row in csv.DictReader(f):
                if row.get("status", "").startswith("error"):
                    n_errors += 1  # retried on resume; row superseded
                    continue
                kept_rows.append(row)
                done.add(
                    (
                        row["batch"],
                        row["set"],
                        row["problem"],
                        row["iteration"],
                        row["algo"],
                        row["params"],
                    )
                )
        if n_errors and not args.simulate:
            # drop the stale error rows so a successful retry doesn't
            # leave two rows per key (consolidate would keep counting
            # the superseded failure); write-then-rename so a crash
            # mid-rewrite can't lose the successful rows
            import tempfile

            d = os.path.dirname(os.path.abspath(args.result_file))
            fd, tmp = tempfile.mkstemp(dir=d, suffix=".csv.tmp")
            try:
                with os.fdopen(fd, "w", newline="") as f:
                    w = csv.DictWriter(f, fieldnames=CSV_FIELDS)
                    w.writeheader()
                    w.writerows(kept_rows)
                os.replace(tmp, args.result_file)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise

    runs = list(iter_runs(spec, base_dir))
    if args.simulate:
        for batch, set_, problem, it, algo, params, options in runs:
            key = _run_key(batch, set_, problem, it, algo, params, base_dir)
            state = "skip" if key in done else "run"
            print(
                f"{state}: [{batch}/{set_}] {os.path.basename(problem)} "
                f"algo={algo} params={params} iteration={it}"
            )
        print(f"{len(runs)} runs total, {len(done)} already done")
        return 0

    from pydcop_tpu.api import solve

    # group consecutive runs that differ only in `iteration` (the
    # innermost loop of iter_runs): each group is one sweep cell
    cells: List[List[Tuple]] = []
    for run in runs:
        if cells and cells[-1][0][:3] + cells[-1][0][4:] == run[:3] + run[4:]:
            cells[-1].append(run)
        else:
            cells.append([run])

    executed = skipped = failed = 0
    handled: set = set()
    with open(args.result_file, "a", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=CSV_FIELDS)
        if not exists:
            writer.writeheader()
        if args.vmap_cells:
            handled, cells_executed, _ = _vmap_cells_pass(
                writer, f, runs, done, base_dir
            )
            executed += cells_executed
        for cell in cells:
            batch, set_, problem, _, algo, params, options = cell[0]
            keys = [
                _run_key(
                    run[0], run[1], run[2], run[3], run[4], run[5],
                    base_dir,
                )
                for run in cell
            ]
            skipped += sum(1 for k in keys if k in done)
            pending = [
                run for run, k in zip(cell, keys)
                if k not in done and k not in handled
            ]
            if not pending:
                continue
            common = dict(
                rounds=int(options.get("rounds", 200)),
                timeout=options.get("timeout"),
                chunk_size=int(options.get("chunk_size", 64)),
                convergence_chunks=int(
                    options.get("convergence_chunks", 0)
                ),
                n_restarts=int(options.get("n_restarts", 1)),
                pad_policy=options.get("pad_policy", "none"),
                **_supervisor_options(options),
            )
            # vmap only plain fixed-round cells: a shared timeout or a
            # best-judged convergence stop would truncate the non-best
            # restarts mid-descent, biasing their cost rows vs what
            # the same spec records sequentially; an n_restarts option
            # already claims the restart axis for best-of-K rows
            if (
                args.vmap_iterations
                and len(pending) == len(cell)  # whole cell fresh
                and len(cell) > 1
                and common["timeout"] is None
                and common["convergence_chunks"] == 0
                and common["n_restarts"] == 1
                and _vmappable(algo)
            ):
                try:
                    result = solve(
                        problem, algo, params, seed=0,
                        **{**common, "n_restarts": len(cell)},
                    )
                    for i, run in enumerate(cell):
                        _write_row(writer, run, {
                            "status": result["status"],
                            "cost": result["restart_costs"][i],
                            "cycle": result["cycle"],
                            # per-iteration share of the cell's totals
                            "msg_count": result["msg_count"] // len(cell),
                            "time": round(result["time"] / len(cell), 6),
                        }, base_dir)
                        executed += 1
                    f.flush()
                    continue
                except Exception:
                    # e.g. the K-fold state OOMs where one run fits —
                    # fall through to the sequential per-run loop
                    # rather than condemning the whole cell
                    pass
            for run in pending:
                it = run[3]
                try:
                    result = solve(problem, algo, params, seed=it, **common)
                except Exception as e:  # record failure, keep sweeping
                    failed += 1
                    result = {
                        "status": f"error: {e}", "cost": "", "cycle": "",
                        "msg_count": "", "time": "",
                    }
                _write_row(writer, run, result, base_dir)
                f.flush()
                executed += 1
    print(
        json.dumps(
            {
                "runs": len(runs),
                "executed": executed,
                "skipped": skipped,
                "failed": failed,
                "result_file": args.result_file,
            }
        )
    )
    return 0 if failed == 0 else 1
