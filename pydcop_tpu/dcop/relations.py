"""Constraints (relations) over finite-domain variables.

Role-equivalent to ``pydcop/dcop/relations.py`` in the reference
(`RelationProtocol`, `NAryMatrixRelation`, `NAryFunctionRelation`,
`constraint_from_str`, assignment/optimal-cost helpers), designed fresh:

- ``NAryMatrixRelation`` is the canonical *host-side* form: an n-dim
  ``numpy`` array indexed by the domain indices of its dimension
  variables.  ``Constraint.as_matrix()`` tabulates any constraint into
  it; the problem compiler then ships those tables to device as
  ``jnp`` arrays (see ``pydcop_tpu.ops.compile``).  The host algebra
  (slice / join / project) exists for setup-time work and parity tests;
  the *solve-time* algebra runs on TPU.
- Function-backed relations (`NAryFunctionRelation`,
  `UnaryFunctionRelation`, `constraint_from_str`) evaluate arbitrary
  Python on the host only.
"""

from __future__ import annotations

import itertools
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
    Union,
)

import numpy as np

from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.utils.expressionfunction import ExpressionFunction
from pydcop_tpu.utils.simple_repr import SimpleRepr, SimpleReprException

DEFAULT_TYPE = np.float32


class RelationProtocol:
    """The minimal protocol every constraint implements.

    Properties: ``name``, ``dimensions`` (list of Variable), ``arity``,
    ``scope_names``, ``shape``.  Calling conventions: positional values in
    dimension order, keyword values by variable name, or a single
    assignment dict.
    """

    @property
    def name(self) -> str:
        raise NotImplementedError

    @property
    def dimensions(self) -> List[Variable]:
        raise NotImplementedError

    @property
    def arity(self) -> int:
        return len(self.dimensions)

    @property
    def scope_names(self) -> List[str]:
        return [v.name for v in self.dimensions]

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(len(v.domain) for v in self.dimensions)

    def __call__(self, *args, **kwargs) -> float:
        raise NotImplementedError

    def get_value_for_assignment(self, assignment) -> float:
        raise NotImplementedError

    def slice(self, partial_assignment: Mapping[str, Any]) -> "Constraint":
        raise NotImplementedError


class AbstractBaseRelation(RelationProtocol, SimpleRepr):
    """Shared plumbing for all constraint implementations."""

    def __init__(self, name: str, variables: Sequence[Variable]):
        self._name = name
        self._variables = list(variables)
        names = [v.name for v in self._variables]
        if len(set(names)) != len(names):
            raise ValueError(
                f"Duplicate variables in constraint {name}: {names}"
            )

    @property
    def name(self) -> str:
        return self._name

    @property
    def dimensions(self) -> List[Variable]:
        return list(self._variables)

    def _resolve_args(self, args, kwargs) -> Dict[str, Any]:
        if args and isinstance(args[0], dict) and len(args) == 1 and not kwargs:
            kwargs = args[0]
            args = ()
        assignment: Dict[str, Any] = {}
        if args:
            if len(args) != len(self._variables):
                raise ValueError(
                    f"Constraint {self._name} expects {len(self._variables)} "
                    f"positional values, got {len(args)}"
                )
            assignment = dict(zip(self.scope_names, args))
        assignment.update(
            {k: v for k, v in kwargs.items() if k in set(self.scope_names)}
        )
        missing = set(self.scope_names) - set(assignment)
        if missing:
            raise ValueError(
                f"Missing value(s) for {missing} in call to constraint "
                f"{self._name}"
            )
        return assignment

    def __call__(self, *args, **kwargs) -> float:
        return self.get_value_for_assignment(self._resolve_args(args, kwargs))

    def value_at(self, assignment: Mapping[str, Any]) -> float:
        return self.get_value_for_assignment(dict(assignment))

    def as_matrix(self) -> "NAryMatrixRelation":
        """Tabulate this constraint into a dense matrix relation.

        This is the bridge to the TPU compiler: every constraint becomes
        a dense cost table over domain indices.
        """
        if isinstance(self, NAryMatrixRelation):
            return self
        shape = self.shape
        arr = np.zeros(shape, dtype=DEFAULT_TYPE)
        domains = [v.domain for v in self._variables]
        names = self.scope_names
        for idx in itertools.product(*(range(s) for s in shape)):
            assignment = {
                names[k]: domains[k][idx[k]] for k in range(len(names))
            }
            arr[idx] = self.get_value_for_assignment(assignment)
        return NAryMatrixRelation(self._variables, arr, name=self._name)

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._name!r}, {self.scope_names})"


Constraint = AbstractBaseRelation  # public alias, as in the reference docs


class NAryMatrixRelation(AbstractBaseRelation):
    """Constraint backed by an n-dimensional cost array.

    Axis ``k`` of the array is indexed by the domain index of the k-th
    dimension variable.  This is the host twin of the device cost tables.

    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> r = NAryMatrixRelation([x, y], [[0, 1], [1, 0]], name='neq')
    >>> r(0, 1)
    1.0
    """

    def __init__(
        self,
        variables: Sequence[Variable],
        matrix: Optional[Union[np.ndarray, list]] = None,
        name: str = "",
    ):
        super().__init__(name, variables)
        shape = tuple(len(v.domain) for v in variables)
        if matrix is None:
            self._m = np.zeros(shape, dtype=DEFAULT_TYPE)
        else:
            self._m = np.asarray(matrix, dtype=DEFAULT_TYPE)
            if self._m.shape != shape:
                raise ValueError(
                    f"Matrix shape {self._m.shape} does not match domain "
                    f"shape {shape} for constraint {name}"
                )

    @property
    def matrix(self) -> np.ndarray:
        return self._m

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._m.shape

    def _indices(self, assignment: Mapping[str, Any]) -> Tuple[int, ...]:
        return tuple(
            v.domain.index(assignment[v.name]) for v in self._variables
        )

    def get_value_for_assignment(self, assignment) -> float:
        if isinstance(assignment, (list, tuple)):
            assignment = dict(zip(self.scope_names, assignment))
        return float(self._m[self._indices(assignment)])

    def set_value_for_assignment(
        self, assignment: Mapping[str, Any], value: float
    ) -> "NAryMatrixRelation":
        """Return a new relation with one cell changed (immutable style)."""
        m = self._m.copy()
        m[self._indices(assignment)] = value
        return NAryMatrixRelation(self._variables, m, name=self._name)

    def slice(self, partial_assignment: Mapping[str, Any]) -> "NAryMatrixRelation":
        """Condition on a partial assignment: fix those axes, keep the rest."""
        if not partial_assignment:
            return self
        unknown = set(partial_assignment) - set(self.scope_names)
        if unknown:
            raise ValueError(
                f"slice: variables {unknown} not in constraint {self._name}"
            )
        index: List[Any] = []
        remaining: List[Variable] = []
        for v in self._variables:
            if v.name in partial_assignment:
                index.append(v.domain.index(partial_assignment[v.name]))
            else:
                index.append(slice(None))
                remaining.append(v)
        sub = self._m[tuple(index)]
        return NAryMatrixRelation(remaining, sub, name=self._name)

    # -- join / projection (DPOP host algebra; device version in ops) ---

    def join(self, other: "Constraint") -> "NAryMatrixRelation":
        """Sum-join: result scope = union of scopes, costs add.

        Implemented as broadcast-add over aligned axes — the same
        formulation the device kernel uses (reference does an explicit
        loop over joint assignments; broadcasting is the array-native
        equivalent).
        """
        other_m = (
            other if isinstance(other, NAryMatrixRelation) else other.as_matrix()
        )
        self_vars = {v.name: v for v in self._variables}
        joined_vars = list(self._variables) + [
            v for v in other_m.dimensions if v.name not in self_vars
        ]
        name_to_axis = {v.name: i for i, v in enumerate(joined_vars)}
        n = len(joined_vars)

        def expand(m: np.ndarray, dims: List[Variable]) -> np.ndarray:
            # Transpose m so its axes are ordered by their position in the
            # joined scope, then reshape with size-1 axes for the missing
            # variables — broadcasting does the rest.
            src_axes = [name_to_axis[v.name] for v in dims]
            shape = [1] * n
            for ax, v in zip(src_axes, dims):
                shape[ax] = len(v.domain)
            order = np.argsort(src_axes)
            m_t = np.transpose(m, order) if m.ndim > 1 else m
            return m_t.reshape(shape)

        a = expand(self._m, self._variables)
        b = expand(other_m.matrix, other_m.dimensions)
        return NAryMatrixRelation(
            joined_vars, a + b, name=f"{self._name}_join_{other_m.name}"
        )

    def project_out(
        self, variable: Union[str, Variable], mode: str = "min"
    ) -> "NAryMatrixRelation":
        """Eliminate one variable by min (or max) over its axis."""
        vname = variable if isinstance(variable, str) else variable.name
        axis = None
        for i, v in enumerate(self._variables):
            if v.name == vname:
                axis = i
                break
        if axis is None:
            raise ValueError(
                f"Cannot project out {vname}: not in scope of {self._name}"
            )
        reducer = np.min if mode == "min" else np.max
        m = reducer(self._m, axis=axis)
        remaining = [v for v in self._variables if v.name != vname]
        return NAryMatrixRelation(remaining, m, name=self._name)

    def argbest_for(
        self, variable: Union[str, Variable], mode: str = "min"
    ) -> Tuple[Any, float]:
        """Best value of ``variable`` after the other axes were eliminated."""
        if self.arity != 1:
            raise ValueError("argbest_for requires a unary relation")
        vals = self._m
        idx = int(np.argmin(vals) if mode == "min" else np.argmax(vals))
        return self._variables[0].domain[idx], float(vals[idx])

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, NAryMatrixRelation)
            and other.scope_names == self.scope_names
            and np.array_equal(other._m, self._m)
        )

    def __hash__(self) -> int:
        # name excluded: __eq__ compares scope + matrix only
        return hash(tuple(self.scope_names))

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY, simple_repr

        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "name": self._name,
            "variables": [simple_repr(v) for v in self._variables],
            "matrix": self._m.tolist(),
        }

    @classmethod
    def _from_repr(cls, r: dict):
        from pydcop_tpu.utils.simple_repr import from_repr

        variables = [from_repr(v) for v in r["variables"]]
        return cls(variables, np.asarray(r["matrix"]), name=r["name"])

    @classmethod
    def from_func_relation(cls, rel: "Constraint") -> "NAryMatrixRelation":
        return rel.as_matrix()


class NAryFunctionRelation(AbstractBaseRelation):
    """Constraint defined by a Python callable (intentional constraint)."""

    def __init__(
        self,
        f: Union[Callable[..., float], ExpressionFunction],
        variables: Sequence[Variable],
        name: str = "",
        f_kwargs: bool = False,
    ):
        super().__init__(name, variables)
        self._f = f
        self._f_kwargs = f_kwargs or isinstance(f, ExpressionFunction)

    @property
    def function(self):
        return self._f

    @property
    def expression(self) -> Optional[str]:
        if isinstance(self._f, ExpressionFunction):
            return self._f.expression
        return None

    def get_value_for_assignment(self, assignment) -> float:
        if isinstance(assignment, (list, tuple)):
            assignment = dict(zip(self.scope_names, assignment))
        if self._f_kwargs:
            return float(
                self._f(**{n: assignment[n] for n in self.scope_names})
            )
        return float(self._f(*(assignment[n] for n in self.scope_names)))

    def slice(self, partial_assignment: Mapping[str, Any]) -> "Constraint":
        if not partial_assignment:
            return self
        if isinstance(self._f, ExpressionFunction):
            fixed = self._f.partial(**dict(partial_assignment))
            remaining = [
                v
                for v in self._variables
                if v.name not in partial_assignment
            ]
            return NAryFunctionRelation(fixed, remaining, name=self._name)
        # generic callable: close over the fixed values
        fixed_vals = dict(partial_assignment)
        remaining = [
            v for v in self._variables if v.name not in partial_assignment
        ]

        def g(**kwargs):
            scope = dict(fixed_vals)
            scope.update(kwargs)
            if self._f_kwargs:
                return self._f(**scope)
            return self._f(*(scope[n] for n in self.scope_names))

        return NAryFunctionRelation(g, remaining, name=self._name, f_kwargs=True)

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY, simple_repr

        if not isinstance(self._f, ExpressionFunction):
            raise SimpleReprException(
                f"Cannot serialize NAryFunctionRelation {self._name} backed "
                "by an arbitrary callable; use an ExpressionFunction"
            )
        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "name": self._name,
            "f": simple_repr(self._f),
            "variables": [simple_repr(v) for v in self._variables],
        }

    @classmethod
    def _from_repr(cls, r: dict):
        from pydcop_tpu.utils.simple_repr import from_repr

        return cls(
            from_repr(r["f"]),
            [from_repr(v) for v in r["variables"]],
            name=r["name"],
        )


class UnaryFunctionRelation(NAryFunctionRelation):
    """Single-variable intentional constraint."""

    def __init__(
        self,
        name: str,
        variable: Variable,
        rel_function: Union[Callable[[Any], float], ExpressionFunction],
    ):
        if isinstance(rel_function, ExpressionFunction):
            f = rel_function
        else:
            vname = variable.name

            def f(**kwargs):
                return rel_function(kwargs[vname])

        super().__init__(f, [variable], name=name, f_kwargs=True)
        self._raw_function = rel_function

    def get_value_for_assignment(self, assignment) -> float:
        if isinstance(assignment, (list, tuple)):
            assignment = dict(zip(self.scope_names, assignment))
        if isinstance(self._raw_function, ExpressionFunction):
            return float(self._f(**assignment))
        return float(self._raw_function(assignment[self.scope_names[0]]))

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY, simple_repr

        if not isinstance(self._raw_function, ExpressionFunction):
            raise SimpleReprException(
                f"Cannot serialize UnaryFunctionRelation {self._name} backed "
                "by an arbitrary callable; use an ExpressionFunction"
            )
        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "name": self._name,
            "variable": simple_repr(self._variables[0]),
            "rel_function": simple_repr(self._raw_function),
        }

    @classmethod
    def _from_repr(cls, r: dict):
        from pydcop_tpu.utils.simple_repr import from_repr

        return cls(r["name"], from_repr(r["variable"]), from_repr(r["rel_function"]))


# ---------------------------------------------------------------------------
# Factory / helper functions (reference-parity API)
# ---------------------------------------------------------------------------


def relation_from_str(
    name: str, expression: str, all_variables: Iterable[Variable]
) -> NAryFunctionRelation:
    """Build an intentional constraint from a Python expression string.

    The constraint's scope is the subset of ``all_variables`` whose names
    appear free in the expression.
    """
    f = ExpressionFunction(expression)
    by_name = {v.name: v for v in all_variables}
    scope: List[Variable] = []
    missing: List[str] = []
    for vname in sorted(f.variable_names):
        if vname in by_name:
            scope.append(by_name[vname])
        else:
            missing.append(vname)
    if missing:
        raise ValueError(
            f"Expression for constraint {name} uses unknown variable(s) "
            f"{missing}: {expression!r}"
        )
    return NAryFunctionRelation(f, scope, name=name)


constraint_from_str = relation_from_str


def constraint_from_external_definition(
    name: str, source_file: str, expression: str, all_variables: Iterable[Variable]
) -> NAryFunctionRelation:
    """Load a constraint whose cost function lives in an external python
    file (reference yaml `type: external` support, simplified)."""
    import runpy

    ns = runpy.run_path(source_file)
    f = ExpressionFunction(expression)
    scope_names = set(f.variable_names)
    by_name = {v.name: v for v in all_variables}
    scope = [by_name[n] for n in sorted(scope_names & set(by_name))]
    fixed = {
        k: v for k, v in ns.items() if k in scope_names and k not in by_name
    }
    if fixed:
        f = f.partial(**fixed)
        scope = [by_name[n] for n in sorted(f.variable_names)]
    return NAryFunctionRelation(f, scope, name=name)


def filter_assignment_dict(
    assignment: Mapping[str, Any], target_vars: Iterable[Variable]
) -> Dict[str, Any]:
    """Keep only the entries of ``assignment`` that concern ``target_vars``."""
    names = {v.name for v in target_vars}
    return {k: v for k, v in assignment.items() if k in names}


def assignment_cost(
    assignment: Mapping[str, Any],
    constraints: Iterable[RelationProtocol],
) -> float:
    """Total cost of a full assignment over the given constraints."""
    cost = 0.0
    for c in constraints:
        cost += c.get_value_for_assignment(
            {n: assignment[n] for n in c.scope_names}
        )
    return cost


def optimal_cost_value(
    variable: Variable, mode: str = "min"
) -> Tuple[Any, float]:
    """Best value (and cost) of a variable w.r.t. its own unary cost."""
    best_v, best_c = None, None
    for val in variable.domain:
        c = variable.cost_for_val(val)
        if best_c is None or (c < best_c if mode == "min" else c > best_c):
            best_v, best_c = val, c
    return best_v, float(best_c)


def find_dependent_relations(
    variable: Variable, relations: Iterable[RelationProtocol]
) -> List[RelationProtocol]:
    """All relations whose scope contains ``variable``."""
    return [r for r in relations if variable.name in r.scope_names]


def add_var_to_rel(
    name: str,
    relation: Constraint,
    variable: Variable,
    f: Callable[[Any, Any], float],
) -> NAryFunctionRelation:
    """Extend a relation with one extra variable combined via ``f(cost, val)``.

    Used by the SECP model builders (reference: relations.add_var_to_rel).
    """
    dims = relation.dimensions + [variable]

    def g(**kwargs):
        base = relation.get_value_for_assignment(
            {n: kwargs[n] for n in relation.scope_names}
        )
        return f(base, kwargs[variable.name])

    return NAryFunctionRelation(g, dims, name=name, f_kwargs=True)
