"""The DCOP problem container.

Role-equivalent to ``pydcop/dcop/dcop.py`` in the reference: objective,
domains, variables, constraints, agents, plus solution-cost evaluation.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple, Union

from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import (
    RelationProtocol,
    assignment_cost,
)
from pydcop_tpu.utils.simple_repr import SimpleRepr


class DCOP(SimpleRepr):
    """A Distributed Constraint Optimization Problem.

    >>> dcop = DCOP('test', objective='min')
    >>> d = Domain('d', '', [0, 1])
    >>> from pydcop_tpu.dcop.objects import Variable
    >>> dcop.add_variable(Variable('x', d))
    >>> 'x' in dcop.variables
    True
    """

    def __init__(
        self,
        name: str = "",
        objective: str = "min",
        description: str = "",
    ):
        if objective not in ("min", "max"):
            raise ValueError(f"objective must be 'min' or 'max', got {objective!r}")
        self._name = name
        self._objective = objective
        self._description = description
        self.domains: Dict[str, Domain] = {}
        self.variables: Dict[str, Variable] = {}
        self.external_variables: Dict[str, Variable] = {}
        self._constraints: Dict[str, RelationProtocol] = {}
        self._agents_def: Dict[str, AgentDef] = {}
        self.dist_hints = None  # DistributionHints, set by yaml loader

    # -- identity ------------------------------------------------------

    @property
    def name(self) -> str:
        return self._name

    @property
    def objective(self) -> str:
        return self._objective

    @property
    def description(self) -> str:
        return self._description

    # -- variables -----------------------------------------------------

    def add_variable(self, v: Variable) -> None:
        from pydcop_tpu.dcop.objects import ExternalVariable

        if v.domain.name not in self.domains:
            self.domains[v.domain.name] = v.domain
        if isinstance(v, ExternalVariable):
            self.external_variables[v.name] = v
        else:
            self.variables[v.name] = v

    def variable(self, name: str) -> Variable:
        return self.variables[name]

    @property
    def all_variables(self) -> List[Variable]:
        return list(self.variables.values()) + list(
            self.external_variables.values()
        )

    # -- constraints ---------------------------------------------------

    def add_constraint(self, c: RelationProtocol) -> None:
        from pydcop_tpu.dcop.objects import ExternalVariable

        for v in c.dimensions:
            if (
                v.name not in self.variables
                and v.name not in self.external_variables
            ):
                self.add_variable(v)
        self._constraints[c.name] = c

    def __iadd__(self, c: RelationProtocol) -> "DCOP":
        self.add_constraint(c)
        return self

    @property
    def constraints(self) -> Dict[str, RelationProtocol]:
        return dict(self._constraints)

    def constraint(self, name: str) -> RelationProtocol:
        return self._constraints[name]

    # -- agents --------------------------------------------------------

    def add_agents(self, agents: Union[Iterable[AgentDef], Mapping[Any, AgentDef]]) -> None:
        if isinstance(agents, Mapping):
            agents = agents.values()
        for a in agents:
            self._agents_def[a.name] = a

    @property
    def agents(self) -> Dict[str, AgentDef]:
        return dict(self._agents_def)

    def agent(self, name: str) -> AgentDef:
        return self._agents_def[name]

    # -- evaluation ----------------------------------------------------

    def solution_cost(
        self, assignment: Mapping[str, Any], infinity: float = float("inf")
    ) -> float:
        """Cost of a full assignment: constraint costs + variable costs."""
        missing = set(self.variables) - set(assignment)
        if missing:
            raise ValueError(f"Assignment misses variable(s) {sorted(missing)}")
        if self.external_variables:
            full = {
                name: ev.value
                for name, ev in self.external_variables.items()
            }
            full.update(assignment)
            assignment = full
        cost = assignment_cost(assignment, self._constraints.values())
        for v in self.variables.values():
            if v.has_cost:
                cost += v.cost_for_val(assignment[v.name])
        return cost

    # -- misc ----------------------------------------------------------

    def __repr__(self) -> str:
        return (
            f"DCOP({self._name!r}, {len(self.variables)} vars, "
            f"{len(self._constraints)} constraints, "
            f"{len(self._agents_def)} agents)"
        )

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY, simple_repr

        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "name": self._name,
            "objective": self._objective,
            "description": self._description,
            "domains": {k: simple_repr(v) for k, v in self.domains.items()},
            "variables": {
                k: simple_repr(v) for k, v in self.variables.items()
            },
            "external_variables": {
                k: simple_repr(v)
                for k, v in self.external_variables.items()
            },
            "dist_hints": simple_repr(self.dist_hints)
            if self.dist_hints is not None
            else None,
            "constraints": {
                k: simple_repr(v) for k, v in self._constraints.items()
            },
            "agents": {
                k: simple_repr(v) for k, v in self._agents_def.items()
            },
        }

    @classmethod
    def _from_repr(cls, r: dict):
        from pydcop_tpu.utils.simple_repr import from_repr

        dcop = cls(r["name"], r["objective"], r.get("description", ""))
        for v in r["variables"].values():
            dcop.add_variable(from_repr(v))
        for v in r.get("external_variables", {}).values():
            dcop.add_variable(from_repr(v))
        for c in r["constraints"].values():
            dcop.add_constraint(from_repr(c))
        dcop.add_agents([from_repr(a) for a in r["agents"].values()])
        if r.get("dist_hints") is not None:
            dcop.dist_hints = from_repr(r["dist_hints"])
        return dcop


def solution_cost(
    dcop: DCOP, assignment: Mapping[str, Any]
) -> float:
    return dcop.solution_cost(assignment)
