"""Problem-model objects: domains, variables, agent definitions.

Role-equivalent to ``pydcop/dcop/objects.py`` in the reference (Domain,
Variable and its cost-carrying variants, AgentDef, bulk helpers), designed
fresh for the TPU build:

- Domains are finite and ordered; every value has a stable integer index.
  The problem compiler (``pydcop_tpu.ops.compile``) uses those indices to
  tabulate costs into device arrays, so *everything* downstream of the
  model is integer-indexed — host objects keep the human-readable values.
- Variables are immutable value objects (hashable by name) so they can be
  dict keys and set members; mutation happens only in solver state arrays.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Tuple, Union

from pydcop_tpu.utils.expressionfunction import ExpressionFunction
from pydcop_tpu.utils.simple_repr import SimpleRepr, SimpleReprException


class Domain(SimpleRepr):
    """A named, ordered, finite set of values.

    >>> d = Domain('colors', 'color', ['R', 'G', 'B'])
    >>> len(d), d.index('G'), d[2]
    (3, 1, 'B')
    """

    def __init__(self, name: str, domain_type: str = "", values: Iterable = ()):
        self._name = name
        self._domain_type = domain_type
        self._values = tuple(values)
        self._index: Dict[Any, int] = {v: i for i, v in enumerate(self._values)}

    @property
    def name(self) -> str:
        return self._name

    @property
    def type(self) -> str:
        return self._domain_type

    @property
    def domain_type(self) -> str:
        return self._domain_type

    @property
    def values(self) -> Tuple:
        return self._values

    def index(self, value: Any) -> int:
        try:
            return self._index[value]
        except KeyError:
            raise ValueError(f"{value!r} is not in domain {self._name}")

    def to_domain_value(self, value: Any):
        """Map a raw (possibly str-parsed) value onto the domain value.

        Used when parsing YAML or CLI input: accepts either the value
        itself or its string form.
        """
        if value in self._index:
            return value
        for v in self._values:
            if str(v) == str(value):
                return v
        raise ValueError(f"{value!r} is not in domain {self._name}")

    def __len__(self) -> int:
        return len(self._values)

    def __iter__(self):
        return iter(self._values)

    def __getitem__(self, i: int):
        return self._values[i]

    def __contains__(self, v: Any) -> bool:
        return v in self._index

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Domain)
            and other._name == self._name
            and other._values == self._values
            and other._domain_type == self._domain_type
        )

    def __hash__(self) -> int:
        return hash((self._name, self._values, self._domain_type))

    def __repr__(self) -> str:
        return f"Domain({self._name!r}, {self._domain_type!r}, {list(self._values)})"

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY, simple_repr

        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "name": self._name,
            "domain_type": self._domain_type,
            "values": [simple_repr(v) for v in self._values],
        }

    @classmethod
    def _from_repr(cls, r: dict):
        return cls(r["name"], r.get("domain_type", ""), r["values"])


# Reference alias (pyDcop calls it VariableDomain in places).
VariableDomain = Domain


class Variable(SimpleRepr):
    """A decision variable with a finite domain."""

    has_cost = False

    def __init__(
        self, name: str, domain: Domain, initial_value: Any = None
    ):
        self._name = name
        if not isinstance(domain, Domain):
            # convenience: accept a raw list of values
            domain = Domain(f"d_{name}", "", domain)
        self._domain = domain
        if initial_value is not None and initial_value not in domain:
            raise ValueError(
                f"Initial value {initial_value!r} not in domain "
                f"{domain.name} of variable {name}"
            )
        self._initial_value = initial_value

    @property
    def name(self) -> str:
        return self._name

    @property
    def domain(self) -> Domain:
        return self._domain

    @property
    def initial_value(self):
        return self._initial_value

    def cost_for_val(self, val: Any) -> float:
        return 0.0

    def clone(self) -> "Variable":
        return Variable(self._name, self._domain, self._initial_value)

    def __eq__(self, other: object) -> bool:
        return (
            type(other) is type(self)
            and other.name == self.name  # type: ignore[union-attr]
            and other.domain == self.domain  # type: ignore[union-attr]
        )

    def __hash__(self) -> int:
        return hash((type(self).__name__, self._name))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._name!r}, {self._domain.name})"


class VariableWithCostDict(Variable):
    """Variable with an explicit per-value cost table."""

    has_cost = True

    def __init__(
        self,
        name: str,
        domain: Domain,
        costs: Mapping[Any, float],
        initial_value: Any = None,
    ):
        super().__init__(name, domain, initial_value)
        self._costs = dict(costs)

    @property
    def costs(self) -> Dict[Any, float]:
        return dict(self._costs)

    def cost_for_val(self, val: Any) -> float:
        return float(self._costs.get(val, 0.0))

    def clone(self) -> "VariableWithCostDict":
        return VariableWithCostDict(
            self._name, self._domain, self._costs, self._initial_value
        )


class VariableWithCostFunc(Variable):
    """Variable whose per-value cost comes from a function of the value.

    The cost function participates in the objective: the compiler
    tabulates ``cost_for_val`` over the domain into a unary cost row.
    """

    has_cost = True

    def __init__(
        self,
        name: str,
        domain: Domain,
        cost_func: Union[Callable[[Any], float], ExpressionFunction],
        initial_value: Any = None,
    ):
        super().__init__(name, domain, initial_value)
        if isinstance(cost_func, ExpressionFunction):
            var_names = list(cost_func.variable_names)
            if len(var_names) != 1:
                raise ValueError(
                    f"Cost function for variable {name} must have exactly "
                    f"one free variable, got {var_names}"
                )
            self._cost_var = var_names[0]
        else:
            self._cost_var = None
        self._cost_func = cost_func

    @property
    def cost_func(self):
        return self._cost_func

    def cost_for_val(self, val: Any) -> float:
        if self._cost_var is not None:
            return float(self._cost_func(**{self._cost_var: val}))
        return float(self._cost_func(val))

    def clone(self) -> "VariableWithCostFunc":
        return VariableWithCostFunc(
            self._name, self._domain, self._cost_func, self._initial_value
        )

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY, simple_repr

        if not isinstance(self._cost_func, ExpressionFunction):
            raise SimpleReprException(
                "Cannot serialize a VariableWithCostFunc built from an "
                "arbitrary callable; use an ExpressionFunction"
            )
        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "name": self._name,
            "domain": simple_repr(self._domain),
            "cost_func": simple_repr(self._cost_func),
            "initial_value": simple_repr(self._initial_value),
        }

    @classmethod
    def _from_repr(cls, r: dict):
        from pydcop_tpu.utils.simple_repr import from_repr

        return cls(
            r["name"],
            from_repr(r["domain"]),
            from_repr(r["cost_func"]),
            from_repr(r.get("initial_value")),
        )


class VariableNoisyCostFunc(VariableWithCostFunc):
    """Cost-function variable with additive uniform noise (deterministic
    per (variable, value) pair, seeded) — used to break symmetry in
    benchmarks, as in the reference."""

    has_cost = True

    def __init__(
        self,
        name: str,
        domain: Domain,
        cost_func,
        initial_value: Any = None,
        noise_level: float = 0.02,
    ):
        super().__init__(name, domain, cost_func, initial_value)
        self._noise_level = noise_level
        rnd = random.Random(name)  # deterministic per variable name
        self._noise = {v: rnd.uniform(0, noise_level) for v in domain}

    @property
    def noise_level(self) -> float:
        return self._noise_level

    def cost_for_val(self, val: Any) -> float:
        return super().cost_for_val(val) + self._noise[val]

    def clone(self) -> "VariableNoisyCostFunc":
        return VariableNoisyCostFunc(
            self._name,
            self._domain,
            self._cost_func,
            self._initial_value,
            self._noise_level,
        )

    def _simple_repr(self) -> dict:
        r = super()._simple_repr()
        r["noise_level"] = self._noise_level
        return r

    @classmethod
    def _from_repr(cls, r: dict):
        from pydcop_tpu.utils.simple_repr import from_repr

        return cls(
            r["name"],
            from_repr(r["domain"]),
            from_repr(r["cost_func"]),
            from_repr(r.get("initial_value")),
            r.get("noise_level", 0.02),
        )


_BINARY_DOMAIN = Domain("binary", "binary", [0, 1])


class BinaryVariable(Variable):
    """A 0/1 variable (used by the repair DCOP and SECP models)."""

    def __init__(self, name: str, initial_value: int = 0):
        super().__init__(name, _BINARY_DOMAIN, initial_value)

    def clone(self) -> "BinaryVariable":
        return BinaryVariable(self._name, self._initial_value)

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY

        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "name": self._name,
            "initial_value": self._initial_value,
        }

    @classmethod
    def _from_repr(cls, r: dict):
        return cls(r["name"], r.get("initial_value", 0))


class ExternalVariable(Variable):
    """A read-only variable whose value is set by the environment (a
    sensor), not by any solver; algorithms treat it as a constant that can
    change between rounds.  Subscribers are notified on change."""

    def __init__(self, name: str, domain: Domain, value: Any = None):
        super().__init__(name, domain, value)
        self._value = value if value is not None else domain[0]
        self._subscribers: List[Callable[[Any], None]] = []

    @property
    def value(self):
        return self._value

    @value.setter
    def value(self, val):
        if val == self._value:
            return
        if val not in self._domain:
            raise ValueError(
                f"Value {val!r} not in domain of external variable {self._name}"
            )
        self._value = val
        for cb in self._subscribers:
            cb(val)

    def subscribe(self, callback: Callable[[Any], None]) -> None:
        self._subscribers.append(callback)

    def unsubscribe(self, callback: Callable[[Any], None]) -> None:
        self._subscribers.remove(callback)

    def clone(self) -> "ExternalVariable":
        return ExternalVariable(self._name, self._domain, self._value)

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY, simple_repr

        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "name": self._name,
            "domain": simple_repr(self._domain),
            "value": simple_repr(self._value),
        }

    @classmethod
    def _from_repr(cls, r: dict):
        from pydcop_tpu.utils.simple_repr import from_repr

        return cls(r["name"], from_repr(r["domain"]), from_repr(r.get("value")))


class AgentDef(SimpleRepr):
    """Definition of an agent: capacity, hosting costs, route costs.

    Hosting and route costs drive the distribution (placement) layer and
    the k-resilient replica placement, as in the reference.
    """

    def __init__(
        self,
        name: str,
        capacity: float = 100.0,
        default_hosting_cost: float = 0.0,
        hosting_costs: Optional[Mapping[str, float]] = None,
        default_route: float = 1.0,
        routes: Optional[Mapping[str, float]] = None,
        **kwargs: Any,
    ):
        self._name = name
        self._capacity = capacity
        self._default_hosting_cost = default_hosting_cost
        self._hosting_costs = dict(hosting_costs or {})
        self._default_route = default_route
        self._routes = dict(routes or {})
        self._extra = dict(kwargs)

    @property
    def name(self) -> str:
        return self._name

    @property
    def capacity(self) -> float:
        return self._capacity

    @property
    def default_hosting_cost(self) -> float:
        return self._default_hosting_cost

    @property
    def hosting_costs(self) -> Dict[str, float]:
        return dict(self._hosting_costs)

    @property
    def default_route(self) -> float:
        return self._default_route

    @property
    def routes(self) -> Dict[str, float]:
        return dict(self._routes)

    @property
    def extra_attrs(self) -> Dict[str, Any]:
        return dict(self._extra)

    def hosting_cost(self, computation: str) -> float:
        return self._hosting_costs.get(computation, self._default_hosting_cost)

    def route(self, other_agent: str) -> float:
        if other_agent == self._name:
            return 0.0
        return self._routes.get(other_agent, self._default_route)

    def __getattr__(self, item: str):
        # expose extra yaml attributes (e.g. "foo: bar" under an agent)
        try:
            return self.__dict__["_extra"][item]
        except KeyError:
            raise AttributeError(item)

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, AgentDef)
            and other._name == self._name
            and other._capacity == self._capacity
            and other._hosting_costs == self._hosting_costs
            and other._routes == self._routes
            and other._default_hosting_cost == self._default_hosting_cost
            and other._default_route == self._default_route
        )

    def __hash__(self) -> int:
        return hash(("AgentDef", self._name))

    def __repr__(self) -> str:
        return f"AgentDef({self._name!r})"

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY, simple_repr

        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "name": self._name,
            "capacity": self._capacity,
            "default_hosting_cost": self._default_hosting_cost,
            "hosting_costs": simple_repr(self._hosting_costs),
            "default_route": self._default_route,
            "routes": simple_repr(self._routes),
            "extra": simple_repr(self._extra),
        }

    @classmethod
    def _from_repr(cls, r: dict):
        from pydcop_tpu.utils.simple_repr import from_repr

        extra = from_repr(r.get("extra", {})) or {}
        return cls(
            r["name"],
            r.get("capacity", 100.0),
            r.get("default_hosting_cost", 0.0),
            from_repr(r.get("hosting_costs", {})) or {},
            r.get("default_route", 1.0),
            from_repr(r.get("routes", {})) or {},
            **extra,
        )


# ---------------------------------------------------------------------------
# Bulk creation helpers (reference: create_variables / create_agents)
# ---------------------------------------------------------------------------


def create_variables(
    name_prefix: str,
    indexes,
    domain: Domain,
    separator: str = "_",
) -> Dict[Union[str, Tuple[str, ...]], Variable]:
    """Create a dict of variables with systematic names.

    >>> vs = create_variables('v', range(3), Domain('d', '', [0, 1]))
    >>> sorted(vs)
    ['v0', 'v1', 'v2']
    """
    variables: Dict[Any, Variable] = {}
    if isinstance(indexes, range):
        indexes = list(indexes)
    if indexes and isinstance(indexes[0], (list, tuple, range)):
        import itertools

        pools = [list(p) for p in indexes]
        for combo in itertools.product(*pools):
            name = name_prefix + separator.join(str(c) for c in combo)
            variables[tuple(str(c) for c in combo)] = Variable(name, domain)
    else:
        for i in indexes:
            name = f"{name_prefix}{i}"
            variables[name] = Variable(name, domain)
    return variables


def create_binary_variables(
    name_prefix: str, indexes, separator: str = "_"
) -> Dict[Any, BinaryVariable]:
    out: Dict[Any, BinaryVariable] = {}
    if isinstance(indexes, range):
        indexes = list(indexes)
    if indexes and isinstance(indexes[0], (list, tuple, range)):
        import itertools

        pools = [list(p) for p in indexes]
        for combo in itertools.product(*pools):
            name = name_prefix + separator.join(str(c) for c in combo)
            out[tuple(str(c) for c in combo)] = BinaryVariable(name)
    else:
        for i in indexes:
            name = f"{name_prefix}{i}"
            out[name] = BinaryVariable(name)
    return out


def create_agents(
    name_prefix: str,
    indexes,
    default_route: float = 1.0,
    routes: Optional[Mapping[str, float]] = None,
    default_hosting_costs: float = 0.0,
    hosting_costs: Optional[Mapping[str, float]] = None,
    capacity: float = 100.0,
) -> Dict[Union[str, Tuple[str, ...]], AgentDef]:
    agents: Dict[Any, AgentDef] = {}
    if isinstance(indexes, range):
        indexes = list(indexes)
    for i in indexes:
        name = f"{name_prefix}{i}"
        agents[name] = AgentDef(
            name,
            capacity=capacity,
            default_hosting_cost=default_hosting_costs,
            hosting_costs=hosting_costs,
            default_route=default_route,
            routes=routes,
        )
    return agents
