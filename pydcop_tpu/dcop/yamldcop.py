"""YAML DCOP format — load/dump, compatible with the reference format.

Role-equivalent to ``pydcop/dcop/yamldcop.py``.  The accepted format
(mirrors the reference's documented schema):

.. code-block:: yaml

    name: graph coloring
    objective: min
    description: optional text

    domains:
      colors:
        values: [R, G, B]        # or ranges: [1 .. 10]
        type: color              # optional
        initial_value: R         # optional (rarely used)

    variables:
      v1:
        domain: colors
        initial_value: R
        cost_function: 0.2 if v1 == 'R' else 0   # optional (yields VariableWithCostFunc)
        noise_level: 0.02                         # optional → VariableNoisyCostFunc

    external_variables:
      e1:
        domain: colors
        initial_value: R

    constraints:
      pref_1:
        type: intention
        function: 10 if v1 == v2 else 0
      ext_1:
        type: extensional
        variables: [v1, v2]
        default: 0
        values:
          10: R R | G G | B B

    agents:                     # mapping (with attributes) or plain list
      a1:
        capacity: 100
        hosting:
          default: 0
          computations: {v1: 5}
        routes:
          default: 1
          a2: 0.5

    distribution_hints:
      must_host:
        a1: [v1]
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Iterable, List, Mapping, Optional, Union

import numpy as np
import yaml

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import (
    AgentDef,
    Domain,
    ExternalVariable,
    Variable,
    VariableNoisyCostFunc,
    VariableWithCostFunc,
)
from pydcop_tpu.dcop.relations import (
    NAryMatrixRelation,
    RelationProtocol,
    constraint_from_external_definition,
    relation_from_str,
)
from pydcop_tpu.dcop.scenario import EventAction, Scenario, ScenarioEvent
from pydcop_tpu.utils.expressionfunction import ExpressionFunction

_RANGE_RE = re.compile(r"^\s*(-?\d+)\s*\.\.\s*(-?\d+)\s*$")


class DcopInvalidFormatError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------


def load_dcop_from_file(filenames: Union[str, Iterable[str]]) -> DCOP:
    """Load a DCOP from one or several YAML files (merged in order)."""
    if isinstance(filenames, (str, os.PathLike)):
        filenames = [filenames]
    content = ""
    main_dir = None
    for fn in filenames:
        if main_dir is None:
            main_dir = os.path.dirname(os.path.abspath(fn))
        with open(fn) as f:
            content += f.read() + "\n"
    return load_dcop(content, main_dir=main_dir)


def load_dcop(yaml_str: str, main_dir: Optional[str] = None) -> DCOP:
    data = yaml.safe_load(yaml_str)
    if not isinstance(data, dict):
        raise DcopInvalidFormatError("DCOP yaml must be a mapping")

    dcop = DCOP(
        name=data.get("name", ""),
        objective=data.get("objective", "min"),
        description=data.get("description", ""),
    )

    domains = _parse_domains(data.get("domains", {}))
    for d in domains.values():
        dcop.domains[d.name] = d

    for v in _parse_variables(data.get("variables", {}), domains):
        dcop.add_variable(v)
    for v in _parse_external_variables(
        data.get("external_variables", {}), domains
    ):
        dcop.add_variable(v)

    for c in _parse_constraints(
        data.get("constraints", {}), dcop, main_dir=main_dir
    ):
        dcop.add_constraint(c)

    dcop.add_agents(_parse_agents(data.get("agents", {})))

    hints = data.get("distribution_hints")
    if hints:
        from pydcop_tpu.distribution.objects import DistributionHints

        dcop.dist_hints = DistributionHints(
            must_host=hints.get("must_host", {}),
            host_with=hints.get("host_with", {}),
        )
    return dcop


def _parse_domain_values(raw_values: Iterable) -> List[Any]:
    values: List[Any] = []
    for v in raw_values:
        if isinstance(v, str):
            m = _RANGE_RE.match(v)
            if m:
                lo, hi = int(m.group(1)), int(m.group(2))
                values.extend(range(lo, hi + 1))
                continue
        values.append(v)
    return values


def _parse_domains(data: Mapping[str, Any]) -> Dict[str, Domain]:
    domains: Dict[str, Domain] = {}
    for name, dd in (data or {}).items():
        if "values" not in dd:
            raise DcopInvalidFormatError(f"Domain {name} has no values")
        values = _parse_domain_values(dd["values"])
        domains[name] = Domain(name, dd.get("type", ""), values)
    return domains


def _parse_variables(
    data: Mapping[str, Any], domains: Mapping[str, Domain]
) -> List[Variable]:
    out: List[Variable] = []
    for name, vd in (data or {}).items():
        vd = vd or {}
        dom_name = vd.get("domain")
        if dom_name is None:
            raise DcopInvalidFormatError(f"Variable {name} has no domain")
        if dom_name not in domains:
            raise DcopInvalidFormatError(
                f"Variable {name} uses unknown domain {dom_name}"
            )
        domain = domains[dom_name]
        initial = vd.get("initial_value")
        if initial is not None:
            initial = domain.to_domain_value(initial)
        cost_expr = vd.get("cost_function")
        if cost_expr is not None:
            cost_f = ExpressionFunction(str(cost_expr))
            free = set(cost_f.variable_names)
            if free != {name}:
                raise DcopInvalidFormatError(
                    f"cost_function of variable {name} must depend only on "
                    f"{name}, got {free}"
                )
            noise = vd.get("noise_level")
            if noise is not None:
                out.append(
                    VariableNoisyCostFunc(
                        name, domain, cost_f, initial, float(noise)
                    )
                )
            else:
                out.append(VariableWithCostFunc(name, domain, cost_f, initial))
        else:
            out.append(Variable(name, domain, initial))
    return out


def _parse_external_variables(
    data: Mapping[str, Any], domains: Mapping[str, Domain]
) -> List[ExternalVariable]:
    out: List[ExternalVariable] = []
    for name, vd in (data or {}).items():
        vd = vd or {}
        domain = domains[vd["domain"]]
        initial = vd.get("initial_value")
        if initial is not None:
            initial = domain.to_domain_value(initial)
        out.append(ExternalVariable(name, domain, initial))
    return out


def _parse_constraints(
    data: Mapping[str, Any], dcop: DCOP, main_dir: Optional[str] = None
) -> List[RelationProtocol]:
    out: List[RelationProtocol] = []
    all_vars = list(dcop.variables.values()) + list(
        dcop.external_variables.values()
    )
    for name, cd in (data or {}).items():
        ctype = cd.get("type")
        if ctype == "intention":
            expr = cd.get("function")
            if expr is None:
                raise DcopInvalidFormatError(
                    f"Intentional constraint {name} has no function"
                )
            source = cd.get("source")
            if source is not None:
                path = (
                    os.path.join(main_dir, source)
                    if main_dir and not os.path.isabs(source)
                    else source
                )
                out.append(
                    constraint_from_external_definition(
                        name, path, str(expr), all_vars
                    )
                )
            else:
                out.append(relation_from_str(name, str(expr), all_vars))
        elif ctype == "extensional":
            out.append(_parse_extensional(name, cd, dcop))
        else:
            raise DcopInvalidFormatError(
                f"Constraint {name}: unknown type {ctype!r} "
                "(expected 'intention' or 'extensional')"
            )
    return out


def _parse_extensional(
    name: str, cd: Mapping[str, Any], dcop: DCOP
) -> NAryMatrixRelation:
    var_names = cd.get("variables")
    if not var_names:
        raise DcopInvalidFormatError(
            f"Extensional constraint {name} has no variables"
        )
    variables = []
    for vn in var_names:
        if vn in dcop.variables:
            variables.append(dcop.variables[vn])
        elif vn in dcop.external_variables:
            variables.append(dcop.external_variables[vn])
        else:
            raise DcopInvalidFormatError(
                f"Extensional constraint {name} uses unknown variable {vn}"
            )
    default = float(cd.get("default", 0))
    shape = tuple(len(v.domain) for v in variables)
    matrix = np.full(shape, default, dtype=np.float32)
    values = cd.get("values", {}) or {}
    for cost, assignments_str in values.items():
        cost = float(cost)
        for assignment_str in str(assignments_str).split("|"):
            tokens = assignment_str.split()
            if len(tokens) != len(variables):
                raise DcopInvalidFormatError(
                    f"Extensional constraint {name}: assignment "
                    f"{assignment_str!r} does not match arity {len(variables)}"
                )
            idx = tuple(
                v.domain.index(v.domain.to_domain_value(t))
                for v, t in zip(variables, tokens)
            )
            matrix[idx] = cost
    return NAryMatrixRelation(variables, matrix, name=name)


def _parse_agents(data) -> List[AgentDef]:
    agents: List[AgentDef] = []
    if data is None:
        return agents
    if isinstance(data, list):
        return [AgentDef(str(a)) for a in data]
    for name, ad in data.items():
        ad = ad or {}
        hosting = ad.get("hosting", {}) or {}
        routes = dict(ad.get("routes", {}) or {})
        default_route = float(routes.pop("default", 1.0))
        extra = {
            k: v
            for k, v in ad.items()
            if k not in ("capacity", "hosting", "routes")
        }
        agents.append(
            AgentDef(
                str(name),
                capacity=float(ad.get("capacity", 100.0)),
                default_hosting_cost=float(hosting.get("default", 0.0)),
                hosting_costs={
                    str(k): float(v)
                    for k, v in (hosting.get("computations", {}) or {}).items()
                },
                default_route=default_route,
                routes={str(k): float(v) for k, v in routes.items()},
                **extra,
            )
        )
    return agents


# ---------------------------------------------------------------------------
# Dumping
# ---------------------------------------------------------------------------


def dcop_yaml(dcop: DCOP) -> str:
    """Serialize a DCOP to the YAML format (inverse of load_dcop for
    matrix/expression constraints)."""
    data: Dict[str, Any] = {
        "name": dcop.name,
        "objective": dcop.objective,
    }
    if dcop.description:
        data["description"] = dcop.description

    data["domains"] = {
        d.name: {
            "values": list(d.values),
            **({"type": d.type} if d.type else {}),
        }
        for d in dcop.domains.values()
    }

    variables = {}
    for v in dcop.variables.values():
        vd: Dict[str, Any] = {"domain": v.domain.name}
        if v.initial_value is not None:
            vd["initial_value"] = v.initial_value
        if isinstance(v, VariableWithCostFunc) and isinstance(
            v.cost_func, ExpressionFunction
        ):
            vd["cost_function"] = v.cost_func.expression
        if isinstance(v, VariableNoisyCostFunc):
            vd["noise_level"] = v.noise_level
        variables[v.name] = vd
    data["variables"] = variables

    if dcop.external_variables:
        data["external_variables"] = {
            v.name: {
                "domain": v.domain.name,
                "initial_value": v.value,
            }
            for v in dcop.external_variables.values()
        }

    constraints: Dict[str, Any] = {}
    for c in dcop.constraints.values():
        expr = getattr(c, "expression", None)
        if expr is not None:
            constraints[c.name] = {"type": "intention", "function": expr}
        else:
            m = c.as_matrix()
            # densest default = most frequent value
            vals, counts = np.unique(m.matrix, return_counts=True)
            default = float(vals[np.argmax(counts)])
            value_lines: Dict[float, List[str]] = {}
            it = np.nditer(m.matrix, flags=["multi_index"])
            for x in it:
                cost = float(x)
                if cost == default:
                    continue
                toks = " ".join(
                    str(v.domain[i])
                    for v, i in zip(m.dimensions, it.multi_index)
                )
                value_lines.setdefault(cost, []).append(toks)
            constraints[c.name] = {
                "type": "extensional",
                "variables": m.scope_names,
                "default": default,
                "values": {
                    cost: " | ".join(lines)
                    for cost, lines in value_lines.items()
                },
            }
    data["constraints"] = constraints

    agents: Dict[str, Any] = {}
    for a in dcop.agents.values():
        ad: Dict[str, Any] = {"capacity": a.capacity}
        if a.default_hosting_cost or a.hosting_costs:
            ad["hosting"] = {
                "default": a.default_hosting_cost,
                **(
                    {"computations": a.hosting_costs}
                    if a.hosting_costs
                    else {}
                ),
            }
        if a.routes or a.default_route != 1.0:
            ad["routes"] = {"default": a.default_route, **a.routes}
        ad.update(a.extra_attrs)
        agents[a.name] = ad
    data["agents"] = agents

    if dcop.dist_hints is not None:
        hints: Dict[str, Any] = {}
        if dcop.dist_hints.must_host_map:
            hints["must_host"] = dcop.dist_hints.must_host_map
        if dcop.dist_hints.host_with_map:
            hints["host_with"] = dcop.dist_hints.host_with_map
        if hints:
            data["distribution_hints"] = hints

    return yaml.safe_dump(data, sort_keys=False, default_flow_style=None)


# ---------------------------------------------------------------------------
# Scenario yaml
# ---------------------------------------------------------------------------


def load_scenario_from_file(filename: str) -> Scenario:
    with open(filename) as f:
        return load_scenario(f.read())


def load_scenario(yaml_str: str) -> Scenario:
    data = yaml.safe_load(yaml_str)
    events = []
    for ed in data.get("events", []):
        if "delay" in ed:
            events.append(
                ScenarioEvent(ed.get("id", ""), delay=float(ed["delay"]))
            )
        else:
            actions = []
            for ad in ed.get("actions", []):
                args = {k: v for k, v in ad.items() if k != "type"}
                actions.append(EventAction(ad["type"], **args))
            events.append(ScenarioEvent(ed.get("id", ""), actions=actions))
    return Scenario(events)


def yaml_scenario(scenario: Scenario) -> str:
    events = []
    for e in scenario:
        if e.is_delay:
            ed: Dict[str, Any] = {"delay": e.delay}
            if e.id:
                ed["id"] = e.id
        else:
            ed = {
                "id": e.id,
                "actions": [
                    {"type": a.type, **a.args} for a in e.actions
                ],
            }
        events.append(ed)
    return yaml.safe_dump({"events": events}, sort_keys=False)
