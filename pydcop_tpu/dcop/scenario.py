"""Timed event scenarios for dynamic DCOP runs.

Role-equivalent to ``pydcop/dcop/scenario.py``: a scenario is an ordered
list of events; an event is either a delay or a list of actions (remove /
add an agent, set an external variable's value).  The orchestrator (host
control plane) replays them during ``run``; on the TPU engine an agent
removal becomes masking the agent's variables out of the batched state
plus a host-side repair step.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

from pydcop_tpu.utils.simple_repr import SimpleRepr


class EventAction(SimpleRepr):
    """A single action: ``type`` in {'add_agent', 'remove_agent',
    'set_value'} with free-form string parameters."""

    def __init__(self, type: str, **args: Any):  # noqa: A002 — reference API
        self._type = type
        self._args = {k: str(v) for k, v in args.items()}

    @property
    def type(self) -> str:
        return self._type

    @property
    def args(self) -> Dict[str, str]:
        return dict(self._args)

    def __repr__(self) -> str:
        return f"EventAction({self._type!r}, {self._args})"

    def __eq__(self, other):
        return (
            isinstance(other, EventAction)
            and other._type == self._type
            and other._args == self._args
        )

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY

        r = {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "type": self._type,
        }
        r.update(self._args)
        return r

    @classmethod
    def _from_repr(cls, r: dict):
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY

        args = {
            k: v for k, v in r.items() if k not in (_CLASS_KEY, _MODULE_KEY, "type")
        }
        return cls(r["type"], **args)


class ScenarioEvent(SimpleRepr):
    """Either a delay (seconds or rounds) or a list of actions."""

    def __init__(
        self,
        id: str = "",  # noqa: A002 — reference API
        delay: Optional[float] = None,
        actions: Optional[List[EventAction]] = None,
    ):
        if (delay is None) == (actions is None):
            raise ValueError("An event is either a delay or a list of actions")
        if actions is not None and not actions:
            raise ValueError("An action event needs at least one action")
        self._id = id
        self._delay = delay
        self._actions = list(actions) if actions is not None else None

    @property
    def id(self) -> str:
        return self._id

    @property
    def is_delay(self) -> bool:
        return self._delay is not None

    @property
    def delay(self) -> Optional[float]:
        return self._delay

    @property
    def actions(self) -> Optional[List[EventAction]]:
        return list(self._actions) if self._actions else None

    def __repr__(self) -> str:
        if self.is_delay:
            return f"ScenarioEvent(delay={self._delay})"
        return f"ScenarioEvent({self._id!r}, actions={self._actions})"

    def __eq__(self, other):
        return (
            isinstance(other, ScenarioEvent)
            and other._id == self._id
            and other._delay == self._delay
            and other._actions == self._actions
        )

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY, simple_repr

        r = {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "id": self._id,
        }
        if self._delay is not None:
            r["delay"] = self._delay
        else:
            r["actions"] = [simple_repr(a) for a in self._actions]
        return r

    @classmethod
    def _from_repr(cls, r: dict):
        from pydcop_tpu.utils.simple_repr import from_repr

        if "delay" in r:
            return cls(r.get("id", ""), delay=r["delay"])
        return cls(
            r.get("id", ""),
            actions=[from_repr(a) for a in r["actions"]],
        )


class Scenario(SimpleRepr):
    """An ordered list of scenario events."""

    def __init__(self, events: Optional[Iterable[ScenarioEvent]] = None):
        self._events = list(events) if events else []

    @property
    def events(self) -> List[ScenarioEvent]:
        return list(self._events)

    def append(self, event: ScenarioEvent) -> None:
        self._events.append(event)

    def __iter__(self):
        return iter(self._events)

    def __len__(self) -> int:
        return len(self._events)

    def __eq__(self, other):
        return isinstance(other, Scenario) and other._events == self._events

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY, simple_repr

        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "events": [simple_repr(e) for e in self._events],
        }

    @classmethod
    def _from_repr(cls, r: dict):
        from pydcop_tpu.utils.simple_repr import from_repr

        return cls([from_repr(e) for e in r["events"]])
