from pydcop_tpu.dcop.objects import (
    AgentDef,
    BinaryVariable,
    Domain,
    ExternalVariable,
    Variable,
    VariableDomain,
    VariableNoisyCostFunc,
    VariableWithCostDict,
    VariableWithCostFunc,
    create_agents,
    create_binary_variables,
    create_variables,
)
from pydcop_tpu.dcop.relations import (
    AbstractBaseRelation,
    Constraint,
    NAryFunctionRelation,
    NAryMatrixRelation,
    RelationProtocol,
    UnaryFunctionRelation,
    assignment_cost,
    constraint_from_str,
    filter_assignment_dict,
    find_dependent_relations,
    optimal_cost_value,
    relation_from_str,
)
from pydcop_tpu.dcop.dcop import DCOP, solution_cost
from pydcop_tpu.dcop.yamldcop import (
    dcop_yaml,
    load_dcop,
    load_dcop_from_file,
    load_scenario,
    load_scenario_from_file,
)
from pydcop_tpu.dcop.scenario import EventAction, Scenario, ScenarioEvent
