import sys

from pydcop_tpu.cli import main

sys.exit(main())
