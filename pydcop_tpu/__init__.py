"""pydcop_tpu — a TPU-native DCOP (Distributed Constraint Optimization) framework.

Re-designed from scratch for TPU hardware (JAX/XLA/pjit/shard_map/pallas)
with the capabilities of the reference library pyDcop (PierreRust/pyDcop).

Layer map (mirrors the reference's public seams, replaces the internals):

- ``pydcop_tpu.utils``      — serialization (SimpleRepr), expression functions.
- ``pydcop_tpu.dcop``       — problem model: Domain/Variable/Constraint/DCOP,
  YAML format (reference: ``pydcop/dcop/``).
- ``pydcop_tpu.graphs``     — computation-graph builders: constraints
  hypergraph, factor graph, pseudo-tree, ordered graph
  (reference: ``pydcop/computations_graph/``).
- ``pydcop_tpu.ops``        — the TPU compute path: the problem compiler
  (DCOP → static pytree of index arrays + cost tables) and the jitted
  array kernels (segment min-plus marginalization, local-gain evaluation,
  UTIL join/project).  This replaces the reference's numpy
  ``NAryMatrixRelation`` hot path.
- ``pydcop_tpu.algorithms`` — the plugin registry + one module per
  algorithm (dsa, mgm, mgm2, maxsum, dpop, ...) with the same contract as
  the reference (``GRAPH_TYPE``, ``build_computation``,
  ``computation_memory``, ``communication_load``, ``algo_params``).
- ``pydcop_tpu.distribution`` — computation→agent placement strategies.
- ``pydcop_tpu.engine``     — the synchronous-batched TPU engine: one
  jitted step = one DCOP round for every agent simultaneously; replaces
  the reference's thread-per-agent runtime for the solve path.
- ``pydcop_tpu.parallel``   — mesh/sharding helpers (shard_map over a
  ``jax.sharding.Mesh``, psum-combined neighbor exchange over ICI).
- ``pydcop_tpu.faults``     — deterministic fault injection for the
  message planes (seeded FaultPlan + ChaosCommunicationLayer wrapper;
  ``docs/faults.md``) — the reproducibility harness behind the
  resilience claims.
- ``pydcop_tpu.infrastructure`` — host-side message-passing runtime
  (agents, messaging, discovery, orchestrator) for capability parity
  with the reference's dynamic/resilient runs, plus the embedding API
  ``solve()``.
- ``pydcop_tpu.commands``   — the CLI (``pydcop-tpu solve|run|graph|...``).
"""

__version__ = "0.1.0"
