"""Sparse (COO) constraint tables — packed storage + gather-based
join kernels for the contraction stack (``docs/performance.md``,
"Sparse table packs").

Every table on device has historically been DENSE (the pyDcop
``NAryMatrixRelation`` heritage): a hard-capped high-arity factor
pays exp(arity) cells that are mostly ``+inf`` — PR 14's bnb
measured a 0.55 pruned-cell fraction on overlap-SECP, evidence most
of the lattice is dead weight.  This module stores the FEASIBLE
tuples only: a :class:`SparseTable` is a sorted COO pack (flat
row-major indices + values) whose absent cells default to the ⊕-
identity — exactly the GAC-style per-scope keep maps of
arXiv:1909.06537, with the join cost bounded output-sensitively per
the FAQ framework (arXiv:1504.04044) instead of by the dense box.

The device contraction of a node whose parts include sparse tables
is a CANDIDATE-LIST join: the host intersects the parts' lifted
supports (a tuple can only be finite where EVERY hard part is
feasible), ships the candidates as ``(sep_id, own_id)`` pairs plus
one flat gather index per part, and the kernel is two gathers and a
segment-reduce — no dense box ever materializes on device.  Shapes
stay static the level-pack way: candidate counts and part pack
lengths pad to pow-2 buckets (:func:`nnz_bucket`), so one executable
serves every node of a bucket (``tools/recompile_guard.py:
run_sparse_guard`` pins at most one extra executable per (semiring,
bucket, dtype, format)).

Exactness rides the existing certificate machinery unchanged:

- idempotent ⊕ — absent tuples are the ⊕-identity, so the segment
  reduce over candidates IS the dense reduce; args/margins follow
  the dense tie-break (lowest own index among minima) and the host
  re-evaluates exact f64 values at the certified arg, so results
  stay BIT-IDENTICAL to the dense sweep.
- mass ⊕ (logsumexp) — absent tuples contribute ``exp(-inf) = 0``
  exactly; a lossy pack (``drop_tol > 0``) carries its truncated-
  mass bound in :attr:`SparseTable.trunc` and the sweep folds it
  into the PR 8 error-bound ledger.
- bnb — incumbents prune the gathered candidate list directly: the
  segment reduce's own row value is the pass-1 bound, for free.

Numpy-only at import (the jax-free surface contract): jax loads
inside :func:`sparse_contraction_kernel` only.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from pydcop_tpu.ops.padding import as_table_dtype

__all__ = [
    "TABLE_FORMATS",
    "as_table_format",
    "SparseTable",
    "pack_table",
    "nnz_bucket",
    "sparse_node_prep",
    "sparse_contraction_kernel",
    "SPARSE_MAX_DENSITY",
    "SPARSE_MIN_CELLS",
    "SPARSE_INDEX_BYTES",
]

#: canonical table format spellings — ``dense`` is the historical
#: stack, ``sparse`` the COO candidate-list path of this module
TABLE_FORMATS = ("dense", "sparse")

_TABLE_FORMAT_ALIASES = {
    "dense": "dense",
    "full": "dense",
    "sparse": "sparse",
    "coo": "sparse",
}


def as_table_format(
    spec: Union[str, None],
    default: str = "dense",
    allowed: Sequence[str] = TABLE_FORMATS,
) -> str:
    """Normalize a ``table_format`` argument to its canonical
    spelling — the sibling of ``ops/padding.py:as_table_dtype``, so
    cache keys and wire partition keys compare strings directly.
    Unknown names raise with a nearest-name suggestion."""
    if spec is None:
        return default
    if not isinstance(spec, str):
        raise ValueError(
            f"table format must be a string, got {spec!r}"
        )
    s = spec.strip().lower()
    if not s:
        return default
    canon = _TABLE_FORMAT_ALIASES.get(s)
    if canon is None or canon not in allowed:
        import difflib

        hint = difflib.get_close_matches(
            s, sorted(set(_TABLE_FORMAT_ALIASES)), n=1
        )
        suggest = (
            f"; did you mean {hint[0]!r}?"
            if hint and _TABLE_FORMAT_ALIASES[hint[0]] in allowed
            else ""
        )
        raise ValueError(
            f"unknown table format {spec!r} (expected one of "
            f"{tuple(allowed)}{suggest})"
        )
    return canon


#: a table qualifies for packing when its non-identity fraction is at
#: most this — below it the index overhead beats the dense cells
SPARSE_MAX_DENSITY = 0.5

#: tables smaller than this never pack: the candidate machinery's
#: fixed cost dwarfs any saving on a few hundred cells
SPARSE_MIN_CELLS = 256

#: per-candidate index overhead the byte budgets charge: sep_id +
#: own_id i32 pairs plus one i32 gather index (``ops/membound.py``
#: adds the per-part value bytes via ``table_dtype_bytes``)
SPARSE_INDEX_BYTES = 12

#: a node falls back to the dense kernels when its candidate list
#: would exceed this fraction of the dense box — past it the gather
#: indices outweigh the cells they skip
SPARSE_MAX_CAND_FRAC = 0.5

#: absolute candidate-list cap per node (i32 buffers; the membound
#: budget governs the real sizing — this is a host-RAM backstop)
SPARSE_MAX_CAND = 1 << 24


def nnz_bucket(n: int) -> int:
    """Pow-2 lattice (floor 8) for candidate counts and pack lengths
    — the static-shape discipline that keeps one compiled executable
    per bucket instead of one per distinct nnz."""
    b = 8
    n = max(int(n), 1)
    while b < n:
        b <<= 1
    return b


class SparseTable:
    """A COO-packed table: sorted flat row-major indices of the
    non-``fill`` cells plus their values; every absent cell IS
    ``fill`` (the consuming ⊕'s identity — ``+inf`` for min-domain
    energies, ``-inf`` for log-weights).

    Quacks like the array the sweeps already pass around —
    ``shape``/``ndim``/``size`` are the DENSE geometry (so cell
    accounting and level keys stay comparable across formats) and
    ``np.asarray`` densifies transparently, so every host fallback
    path stays correct without a special case.  ``nbytes`` is the
    PACKED payload — what ``engine/memo.py`` fingerprints and the
    byte budgets charge."""

    __slots__ = ("shape", "flat", "vals", "fill", "trunc")

    def __init__(
        self,
        shape: Tuple[int, ...],
        flat: np.ndarray,
        vals: np.ndarray,
        fill: float,
        trunc: float = 0.0,
    ):
        self.shape = tuple(int(s) for s in shape)
        self.flat = np.ascontiguousarray(flat, dtype=np.int64)
        self.vals = np.ascontiguousarray(vals, dtype=np.float64)
        self.flat.setflags(write=False)
        self.vals.setflags(write=False)
        self.fill = float(fill)
        #: truncated-mass bound (nats) of a lossy pack — 0.0 for an
        #: exact pack; the mass-⊕ ledger folds it in per use
        self.trunc = float(trunc)

    # -- array-protocol geometry -----------------------------------------

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def size(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    @property
    def nnz(self) -> int:
        return int(self.flat.size)

    @property
    def density(self) -> float:
        return self.nnz / max(self.size, 1)

    @property
    def nbytes(self) -> int:
        """PACKED bytes (indices + values) — the memo/budget unit."""
        return int(self.flat.nbytes + self.vals.nbytes)

    def __array__(self, dtype=None, copy=None):
        d = self.todense()
        return d if dtype is None else d.astype(dtype)

    def todense(self) -> np.ndarray:
        out = np.full(self.size, self.fill, dtype=np.float64)
        out[self.flat] = self.vals
        return out.reshape(self.shape)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"SparseTable(shape={self.shape}, nnz={self.nnz}, "
            f"fill={self.fill}, trunc={self.trunc})"
        )

    # -- exact host gathers ----------------------------------------------

    def finite_amax(self) -> float:
        """Largest |finite| packed value (0.0 when none) — the
        sparse fast path of ``ops/semiring.py:_finite_amax`` (the
        fill is an exact identity, never a rounding scale)."""
        v = self.vals[np.isfinite(self.vals)]
        return float(np.max(np.abs(v))) if v.size else 0.0

    def lookup(self, flat_idx: np.ndarray) -> np.ndarray:
        """Exact f64 values at flat row-major indices (vectorized
        searchsorted; misses return ``fill``)."""
        fi = np.asarray(flat_idx, dtype=np.int64)
        pos = np.searchsorted(self.flat, fi)
        pos_c = np.minimum(pos, max(self.flat.size - 1, 0))
        hit = (
            (self.flat[pos_c] == fi)
            if self.flat.size
            else np.zeros(fi.shape, dtype=bool)
        )
        out = np.full(fi.shape, self.fill, dtype=np.float64)
        if self.flat.size:
            out[hit] = self.vals[pos_c[hit]]
        return out

    def gather(self, idx: Tuple[Any, ...]) -> np.ndarray:
        """Fancy-index gather (arrays/ints, broadcasting like numpy
        advanced indexing) — what the exact-f64 host glue calls in
        place of ``np.asarray(table)[idx]``."""
        arrs = np.broadcast_arrays(
            *[np.asarray(i, dtype=np.int64) for i in idx]
        )
        flat = np.zeros(arrs[0].shape, dtype=np.int64)
        stride = 1
        for ax in range(self.ndim - 1, -1, -1):
            flat += arrs[ax] * stride
            stride *= self.shape[ax]
        return self.lookup(flat)

    def contains(self, flat_idx: np.ndarray) -> np.ndarray:
        fi = np.asarray(flat_idx, dtype=np.int64)
        if not self.flat.size:
            return np.zeros(fi.shape, dtype=bool)
        pos = np.minimum(
            np.searchsorted(self.flat, fi), self.flat.size - 1
        )
        return self.flat[pos] == fi

    def positions(self, flat_idx: np.ndarray) -> np.ndarray:
        """Pack positions of flat indices KNOWN to be present — the
        per-part gather indices the device kernel consumes."""
        return np.searchsorted(self.flat, flat_idx).astype(np.int64)


def pack_table(
    table: np.ndarray,
    fill: float,
    *,
    max_density: float = SPARSE_MAX_DENSITY,
    min_cells: int = SPARSE_MIN_CELLS,
    drop_tol: float = 0.0,
) -> Optional[SparseTable]:
    """COO-pack a dense table whose cells default to ``fill``, or
    None when packing would not pay (too small, or too dense).

    ``drop_tol`` (mass ⊕ only, ``fill = -inf``): additionally drop
    near-identity log-weight cells whose TOTAL mass is at most
    ``drop_tol`` of the table's peak-mass bound; the dropped mass is
    bounded in :attr:`SparseTable.trunc` (nats) and the sweeps fold
    it into the error-bound ledger — the value answer stays within
    the reported bound, never silently truncated."""
    a = np.asarray(table, dtype=np.float64)
    if a.size < min_cells:
        return None
    flat = a.reshape(-1)
    if np.isnan(fill):  # pragma: no cover - identities are ±inf/0
        keep = ~np.isnan(flat)
    else:
        keep = flat != fill
    trunc = 0.0
    if drop_tol > 0.0 and np.isneginf(fill):
        finite = np.isfinite(flat)
        if finite.any():
            vmax = float(np.max(flat[finite]))
            # cells below this threshold sum to <= drop_tol·e^vmax
            # <= drop_tol × the table's own mass: a relative mass
            # truncation of at most drop_tol, i.e. a log-value error
            # bounded by -log(1 - drop_tol)
            thr = vmax + np.log(drop_tol / max(a.size, 1))
            dropped = keep & (flat <= thr)
            if dropped.any():
                keep = keep & ~dropped
                trunc = -np.log1p(-min(drop_tol, 0.5))
    nnz = int(keep.sum())
    if nnz > max_density * a.size:
        return None
    idx = np.flatnonzero(keep).astype(np.int64)
    return SparseTable(a.shape, idx, flat[idx], fill, trunc)


# -- candidate-list node prep -------------------------------------------


class SparsePrep:
    """Host-side candidate-list join of one contraction node: the
    kernel ABI buffers plus the static bucket geometry."""

    __slots__ = (
        "sep_ids", "own_ids", "gidx", "part_flats", "n_cand",
        "n_seg", "d_own", "n_cand_b", "n_seg_b", "part_lens_b",
        "trunc",
    )

    def __init__(
        self, sep_ids, own_ids, gidx, part_flats, n_seg, d_own,
        trunc,
    ):
        self.sep_ids = sep_ids
        self.own_ids = own_ids
        self.gidx = gidx  # one i64[n_cand] per part
        self.part_flats = part_flats  # one f64[len_p] per part
        self.n_cand = int(sep_ids.size)
        self.n_seg = int(n_seg)
        self.d_own = int(d_own)
        self.n_cand_b = nnz_bucket(self.n_cand)
        self.n_seg_b = nnz_bucket(self.n_seg)
        self.part_lens_b = tuple(
            nnz_bucket(f.size) for f in part_flats
        )
        self.trunc = float(trunc)

    @property
    def key(self) -> tuple:
        """The static geometry that joins the level-pack bucket key:
        two nodes with equal keys ride one vmapped dispatch."""
        return (self.n_cand_b, self.n_seg_b, self.part_lens_b)

    @property
    def table_bytes(self) -> int:
        """Real per-row device allocation: candidate index buffers
        plus the packed part values (the number ``max_util_bytes``
        and the supervisor's capacity model size against)."""
        return self.n_cand_b * (
            8 + 4 * len(self.part_flats)
        ) + 8 * sum(self.part_lens_b)


def sparse_node_prep(
    parts: Sequence[Tuple[List[str], Any]],
    target: Sequence[str],
    shape: Sequence[int],
    identity: float,
) -> Optional[SparsePrep]:
    """Build the candidate-list join for one node, or None when no
    part is sparse or the intersection would not pay (the caller
    falls back to the dense kernels — ``semiring.sparse_fallbacks``).

    Candidates are the intersection of the sparse parts' supports
    lifted to the target grid: a joined tuple can be non-identity
    only where EVERY sparse part is feasible, so the list covers
    exactly the potentially-finite cells and absent cells are the
    ⊕-identity — the exactness argument of the module docstring.
    Each candidate carries ``(sep_id, own_id)`` plus one gather
    index per part (dense parts index their own flat box; sparse
    parts index their packed values), computed here in vectorized
    numpy so the kernel is pure gather + segment-reduce."""
    shape = tuple(int(s) for s in shape)
    target = list(target)
    nd = len(target)
    size = 1
    for s in shape:
        size *= s
    sparse_parts = [
        (i, dims, t)
        for i, (dims, t) in enumerate(parts)
        if isinstance(t, SparseTable)
    ]
    if not sparse_parts:
        return None

    # seed: the sparse part whose lifted support is smallest — its
    # nnz times the free extent of the target dims it does not cover
    def lifted(entry):
        _, dims, t = entry
        free = 1
        for d, s in zip(target, shape):
            if d not in dims:
                free *= s
        return t.nnz * free

    seed_i, seed_dims, seed_t = min(sparse_parts, key=lifted)
    est = lifted((seed_i, seed_dims, seed_t))
    if est > SPARSE_MAX_CAND_FRAC * size or est > SPARSE_MAX_CAND:
        return None

    # per-target-dim candidate coordinates, built from the seed's
    # unraveled support crossed with the uncovered dims
    coords: Dict[str, np.ndarray] = {}
    seed_coords = np.unravel_index(
        seed_t.flat, seed_t.shape
    )
    for d, c in zip(seed_dims, seed_coords):
        coords[d] = c.astype(np.int64)
    n = seed_t.nnz
    for d, s in zip(target, shape):
        if d in coords:
            continue
        for k in coords:
            coords[k] = np.repeat(coords[k], s)
        coords[d] = np.tile(np.arange(s, dtype=np.int64), n)
        n *= s

    # filter through every other sparse part's support
    for i, dims, t in sparse_parts:
        if i == seed_i:
            continue
        pflat = np.zeros(n, dtype=np.int64)
        stride = 1
        for ax in range(len(dims) - 1, -1, -1):
            pflat += coords[dims[ax]] * stride
            stride *= t.shape[ax]
        hit = t.contains(pflat)
        if not hit.all():
            for k in coords:
                coords[k] = coords[k][hit]
            n = int(hit.sum())
    if n == 0:
        # a fully-infeasible node: one sentinel candidate at the
        # identity keeps the kernel ABI non-degenerate; the segment
        # reduce still reports every cell at the ⊕-identity
        for k in coords:
            coords[k] = np.zeros(1, dtype=np.int64)
        n = 1

    # sort by target flat id so per-segment candidate runs are
    # contiguous (indices_are_sorted on device, binary-search host
    # repair) — flat ids are unique by construction
    tflat = np.zeros(n, dtype=np.int64)
    stride = 1
    for ax in range(nd - 1, -1, -1):
        tflat += coords[target[ax]] * stride
        stride *= shape[ax]
    order = np.argsort(tflat, kind="stable")
    for k in coords:
        coords[k] = coords[k][order]

    d_own = shape[-1]
    own_ids = coords[target[-1]].astype(np.int64)
    sep_ids = (tflat[order] // d_own).astype(np.int64)
    n_seg = size // max(d_own, 1)

    gidx: List[np.ndarray] = []
    part_flats: List[np.ndarray] = []
    trunc = 0.0
    for i, (dims, t) in enumerate(parts):
        pflat = np.zeros(n, dtype=np.int64)
        stride = 1
        pshape = (
            t.shape
            if isinstance(t, SparseTable)
            else np.asarray(t).shape
        )
        for ax in range(len(dims) - 1, -1, -1):
            pflat += coords[dims[ax]] * stride
            stride *= pshape[ax]
        if isinstance(t, SparseTable):
            # every candidate hits by construction (the intersection
            # above filtered through this part's support) — except a
            # degenerate all-infeasible node's sentinel, which the
            # clamp below maps to SOME packed value; its join value
            # is irrelevant (every output cell is the identity)
            pos = np.minimum(
                t.positions(pflat), max(t.nnz - 1, 0)
            )
            gidx.append(pos)
            part_flats.append(t.vals)
            trunc += t.trunc
        else:
            gidx.append(pflat)
            part_flats.append(
                np.asarray(t, dtype=np.float64).reshape(-1)
            )
    return SparsePrep(
        sep_ids, own_ids, tuple(gidx), tuple(part_flats),
        n_seg, d_own, trunc,
    )


# -- the gather/segment-reduce kernels ----------------------------------

_SPARSE_KERNELS: Dict[Tuple, Any] = {}
_SPARSE_KERNELS_MAX = 128


def sparse_contraction_kernel(
    sr,
    n_cand_b: int,
    n_seg_b: int,
    part_lens_b: Tuple[int, ...],
    bnb: bool = False,
    table_dtype: str = "f32",
):
    """Jit-compiled sparse contraction for one candidate bucket:
    per-part value gathers summed into the f32 accumulator, then a
    segment-⊕ over the (sorted) separator ids — always batched over
    a leading stack axis, mirroring the level-pack dispatches.

    ABI per row (after the optional bnb ``budget`` f32 scalar and
    int8 ``scales``/``offsets`` f32[P] dequant params):
    ``sep_ids i32[n_cand_b]`` (ghost candidates carry ``n_seg_b``,
    an extra segment sliced off), ``own_ids i32[n_cand_b]``, then
    per part ``vals dtype[len_p]`` + ``gidx i32[n_cand_b]``.

    Outputs match :func:`~pydcop_tpu.ops.semiring.
    contraction_kernel` exactly — idempotent ⊕ returns ``(arg,
    margins[, keep])`` (values re-evaluated on host at the certified
    arg), mass ⊕ returns ``(vals[, keep, discard])`` — so the same
    ``_finish_device_row`` certification/repair glue consumes both
    formats.  Ties break like the dense kernels: the LOWEST own
    index among the minima (candidates are unique per (sep, own)
    cell), and a cell with no candidate reports the ⊕-identity with
    the same ``arg=0`` / NaN-margin signature an all-identity dense
    row produces — bit-parity by construction.
    """
    from pydcop_tpu.ops.semiring import get_semiring

    sr = get_semiring(sr)
    table_dtype = as_table_dtype(table_dtype)
    if sr.kind in ("kbest", "expectation"):
        raise ValueError(
            f"sparse contraction supports scalar ⊕ only, not "
            f"{sr.name!r} (structured cells keep the dense kernels)"
        )
    key = (
        sr.name, int(n_cand_b), int(n_seg_b), tuple(part_lens_b),
        bool(bnb), table_dtype,
    )
    fn = _SPARSE_KERNELS.get(key)
    if fn is not None:
        return fn
    if len(_SPARSE_KERNELS) >= _SPARSE_KERNELS_MAX:
        _SPARSE_KERNELS.pop(next(iter(_SPARSE_KERNELS)))
    import jax
    import jax.numpy as jnp

    from pydcop_tpu.ops.padding import INT8_NEG_INF, INT8_POS_INF

    P = len(part_lens_b)
    S1 = int(n_seg_b) + 1  # + the ghost segment of padded candidates
    idem = bool(sr.idempotent)
    lo = idem and not sr.maximize
    ident = np.float32(sr.plus_identity)
    SENT = jnp.int32(1 << 30)

    def _seg_red(v, sep, maximize):
        f = jax.ops.segment_max if maximize else jax.ops.segment_min
        return f(
            v, sep, num_segments=S1, indices_are_sorted=True
        )

    def _join(sep, tabs, gidxs):
        v = jnp.zeros((int(n_cand_b),), dtype=jnp.float32)
        for t, g in zip(tabs, gidxs):
            v = v + jnp.take(
                t.astype(jnp.float32), g, axis=0,
                mode="clip",
            )
        return v

    def _mass_u(v, sep):
        m = _seg_red(v, sep, True)
        safe = jnp.where(jnp.isfinite(m), m, 0.0)
        e = jnp.where(
            jnp.isfinite(v), jnp.exp(v - safe[sep]), 0.0
        )
        # +inf log-weights (hard -inf energies) must stay absorbing,
        # exactly like the dense kernel's isfinite(m) guard
        e = jnp.where(jnp.isposinf(v), jnp.inf, e)
        s = jax.ops.segment_sum(
            e, sep, num_segments=S1, indices_are_sorted=True
        )
        return jnp.where(
            jnp.isfinite(m), safe + jnp.log(s), m
        )

    def _discard(rowb, keep):
        pr = jnp.where(keep, -jnp.inf, rowb)
        m = jnp.max(pr)
        safe = jnp.where(jnp.isfinite(m), m, 0.0)
        s = jnp.sum(
            jnp.where(jnp.isfinite(pr), jnp.exp(pr - safe), 0.0)
        )
        return jnp.where(
            (s > 0) & jnp.isfinite(m), safe + jnp.log(s), -jnp.inf
        )

    if idem:

        def _idem_core(sep, own, *tg):
            tabs, gidxs = tg[:P], tg[P:]
            v = _join(sep, tabs, gidxs)
            u = _seg_red(v, sep, sr.maximize)
            best = v == u[sep]
            ownm = jnp.where(best, own, SENT)
            arg_s = jax.ops.segment_min(
                ownm, sep, num_segments=S1,
                indices_are_sorted=True,
            )
            arg = jnp.where(arg_s >= SENT, 0, arg_s)
            # margins against the NEXT cell: mask the one candidate
            # at (sep, arg) — absent cells are the identity, so an
            # empty remainder reports the identity, exactly like the
            # dense one-hot mask over a mostly-identity row
            excl = own == arg_s[sep]
            v2 = jnp.where(excl, ident, v)
            second = _seg_red(v2, sep, sr.maximize)
            margins = (
                second - u if lo else u - second
            )
            return arg, margins, u

        if bnb:

            def contract(budget, sep, own, *tg):
                arg, margins, u = _idem_core(sep, own, *tg)
                # the segment reduce IS the row's ⊕-extremum — the
                # pass-1 bound is free.  Negated comparisons keep
                # NaN bounds (cancelling ±inf parts) conservative.
                keep = (
                    jnp.logical_not(u > budget)
                    if lo
                    else jnp.logical_not(u < budget)
                )
                return arg, jnp.where(keep, margins, jnp.inf), keep

        else:

            def contract(sep, own, *tg):
                arg, margins, _ = _idem_core(sep, own, *tg)
                return arg, margins

    elif bnb:

        def contract(budget, sep, own, *tg):
            tabs, gidxs = tg[:P], tg[P:]
            v = _join(sep, tabs, gidxs)
            u = _mass_u(v, sep)
            keep = jnp.logical_not(u < budget)
            # the ghost segment must not leak into the measured
            # discard: its identity (-inf) never clears any budget,
            # so slice it off the discard entirely
            return (
                jnp.where(keep, u, -jnp.inf),
                keep,
                _discard(u[:-1], keep[:-1]),
            )

    else:

        def contract(sep, own, *tg):
            tabs, gidxs = tg[:P], tg[P:]
            v = _join(sep, tabs, gidxs)
            return (_mass_u(v, sep),)

    if table_dtype == "int8":
        inner = contract

        def contract(*args):  # noqa: F811 — int8 dequant wrap
            if bnb:
                budget, scales, offsets, sep, own, *tg = args
            else:
                scales, offsets, sep, own, *tg = args
            qtabs, gidxs = tg[:P], tg[P:]
            tabs = []
            for i, q in enumerate(qtabs):
                f = q.astype(jnp.float32) * scales[i] + offsets[i]
                f = jnp.where(q == INT8_POS_INF, jnp.inf, f)
                f = jnp.where(q == INT8_NEG_INF, -jnp.inf, f)
                tabs.append(f)
            rest = tuple(tabs) + tuple(gidxs)
            return (
                inner(budget, sep, own, *rest)
                if bnb
                else inner(sep, own, *rest)
            )

    from pydcop_tpu.telemetry.jit import profiled_jit

    fn = profiled_jit(
        jax.vmap(contract),
        label=f"sparse-{sr.name}"
        + ("-bnb" if bnb else "")
        + ("" if table_dtype == "f32" else f"-{table_dtype}"),
    )
    _SPARSE_KERNELS[key] = fn
    return fn


def np_table_format_dtype(table_dtype: str):
    """Numpy storage dtype for packed part values — mirrors
    ``ops/semiring.py:_np_table_dtype`` without importing it (the
    dispatch glue needs both modules; keep the import edge one-way:
    semiring → sparse)."""
    table_dtype = as_table_dtype(table_dtype)
    if table_dtype == "bf16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    if table_dtype == "int8":
        return np.dtype(np.int8)
    return np.dtype(np.float32)
