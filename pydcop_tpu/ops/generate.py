"""Array-level instance generators — the fast path for big problems.

The CLI generators (``pydcop_tpu/commands/generators/``) produce DCOP
*model objects* for reference-format YAML parity; building a million
Python ``Variable``/``Constraint`` objects costs minutes.  These
generators produce the numpy arrays :func:`~pydcop_tpu.ops.compile
.compile_from_arrays` consumes directly — the same problem families at
~1e6 variables in around a second.

Role-equivalence note: the reference generates its benchmark instances
as YAML via ``pydcop/commands/generators/`` and could not reach this
scale at all (its thread-per-agent runtime tops out around 1e3 agents
per host); the array path is what lets the TPU engine demonstrate the
headroom above that.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def coloring_arrays(
    n_vars: int,
    colors: int = 3,
    degree: int = 3,
    seed: int = 0,
    noise: float = 0.02,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Random soft graph coloring: ``(scopes, table, unary)``.

    Same family as ``__graft_entry__._make_coloring_dcop`` / the CLI
    ``generate graph_coloring`` command: each variable proposes
    ``degree`` random neighbors (self-loops and duplicate edges
    dropped), every edge pays cost 1 when its endpoints pick the same
    color, and tiny noisy unary preferences (level ``noise``) break the
    symmetry.

    Returns arrays for :func:`compile_from_arrays`: ``scopes i32[m,2]``,
    the shared ``table f32[colors, colors]`` (identity penalty), and
    ``unary f32[n_vars, colors]``.
    """
    rng = np.random.default_rng(seed)
    i = np.repeat(np.arange(n_vars, dtype=np.int64), degree)
    j = rng.integers(0, n_vars, size=n_vars * degree)
    a, b = np.minimum(i, j), np.maximum(i, j)
    keep = a != b
    pairs = np.unique(
        np.stack([a[keep], b[keep]], axis=1), axis=0
    ).astype(np.int32)
    table = np.eye(colors, dtype=np.float32)
    unary = (noise * rng.random((n_vars, colors))).astype(np.float32)
    return pairs, table, unary


def ising_arrays(
    rows: int,
    cols: int,
    seed: int = 0,
    bin_range: float = 1.6,
    un_range: float = 0.05,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Toroidal Ising grid: ``(scopes, tables, unary)``.

    The classic DCOP benchmark family (reference: ``pydcop generate
    ising``): spin variables on a ``rows x cols`` torus, random
    symmetric pairwise couplings in ``[-bin_range, bin_range]`` and
    random unary fields in ``[-un_range, un_range]``.  Tables are
    per-edge here (couplings differ), ``f32[m, 2, 2]``.
    """
    rng = np.random.default_rng(seed)
    n = rows * cols
    idx = np.arange(n, dtype=np.int64).reshape(rows, cols)
    right = np.stack([idx, np.roll(idx, -1, axis=1)], axis=-1)
    down = np.stack([idx, np.roll(idx, -1, axis=0)], axis=-1)
    pairs = np.concatenate(
        [right.reshape(-1, 2), down.reshape(-1, 2)]
    )
    # torus wrap can duplicate an edge when a dimension has size <= 2
    a = pairs.min(axis=1)
    b = pairs.max(axis=1)
    keep = a != b
    pairs = np.unique(np.stack([a[keep], b[keep]], axis=1), axis=0)
    m = len(pairs)
    k = rng.uniform(-bin_range, bin_range, size=m).astype(np.float32)
    # cost(si, sj) = k if si == sj else -k  (spins in {0, 1})
    eye = np.eye(2, dtype=np.float32)
    tables = k[:, None, None] * (2.0 * eye - 1.0)[None]
    unary_r = rng.uniform(-un_range, un_range, size=n).astype(np.float32)
    unary = np.stack([-unary_r, unary_r], axis=1)
    return pairs.astype(np.int32), tables, unary
