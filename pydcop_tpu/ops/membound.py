"""Memory-bounded contraction: cut-set planning + budgeted sweeps so
exact solves and inference survive induced widths past HBM
(``docs/semirings.md``, "Memory-bounded contraction").

The level-synchronous sweeps (DPOP's UTIL phase, the semiring
contraction engine) die the moment ONE joined UTIL/message table
exceeds device memory — table size is exponential in induced width,
so a single wide separator kills the whole call however small the
rest of the tree is.  This module bounds that largest table to a
``max_util_bytes`` budget the MB-DPOP way (RMB-DPOP,
arXiv:2002.10641): walk the bucket-tree plan
(``ops/semiring.py:build_plan``), and for every contraction whose
projected table would exceed the budget choose a minimal CUT SET of
separator variables to condition on — preferring variables shared
across many oversized nodes, so one enumeration is reused by every
sibling that needs it (the redundancy elimination that distinguishes
RMB from plain MB).  Each joint assignment of the cut set is one
LANE: a conditioned copy of the plan whose cut domains are singletons
(axes kept, length 1), so every lane has IDENTICAL table shapes and
the lanes ride the existing level-pack stack machinery
(``contract_sweep`` / ``_util_phase_multi``) as extra rows of the
vmapped leading axis — same bucketing, same per-semiring kernel
cache, zero new kernel shapes beyond the conditioned axes.

Before planning, a CROSS-EDGE CONSISTENCY pass (after arXiv:
1909.06537) shrinks domains: a value whose every completion under
some constraint is hard-infeasible (``+inf`` energy) can never appear
in an optimum and carries ``exp(-inf) = 0`` weight, so pruning it is
exact for EVERY registered ⊕ — and smaller domains mean budgets are
met with fewer cut variables (``membound.pruned_cells``).

Per-⊕ exactness contracts carry over unchanged: each lane is a
normal sweep, so idempotent ⊕ keeps the f32 arg certificate + exact
host-f64 values PER LANE and the ⊕-combine across lanes (min/max of
exact scalars) is exact; logsumexp ⊕ carries its accumulated error
bound per lane and the cross-lane combine bounds the result by the
WORST lane bound plus the f64 combine rounding (multiplicative
errors: ``Σ ẑ_l ∈ Σ z_l · [e^-max(e_l), e^max(e_l)]``).

OOM ladder position (``docs/faults.md`` recovery matrix): a budgeted
sweep turns the supervisor's device-OOM signal into a REPLAN instead
of a host retreat.  Level-stack OOM still degrades to per-node
dispatches; a per-node OOM then re-plans the whole sweep at HALF the
budget (``membound.replans`` — deterministic: the plan is a pure
function of (graph, budget)), and only when the budget bottoms out
does the sweep abandon the device for bounded host f64.  The
injected ``device_oom_bytes=N`` chaos capacity model
(``faults/plan.py``) exercises exactly this: dispatches whose
per-lane joined table exceeds N bytes OOM deterministically, so
halving converges the moment the planned tables fit — like real HBM.

Budget semantics: ``max_util_bytes`` bounds the f32 bytes
(``BYTES_PER_CELL`` = 4) of each individual joined UTIL/message
table.  Stack height (lanes × level rows) multiplies a dispatch
LINEARLY and is handled by the existing level→node ladder; the
budget caps the per-table term that is EXPONENTIAL in width — the
one no ladder can save.

This module is numpy-only at import, like ``ops/semiring.py``.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pydcop_tpu.ops.semiring import (
    ContractionPlan,
    Semiring,
    _np_logsumexp,
    contract_sweep,
)

from pydcop_tpu.ops.padding import as_table_dtype, table_dtype_bytes

_EPS64 = float(np.finfo(np.float64).eps)

#: device tables default to f32 — the byte unit ``max_util_bytes``
#: caps.  Sub-f32 table packs (``table_dtype=bf16|int8``) shrink the
#: per-cell width through :func:`~pydcop_tpu.ops.padding.
#: table_dtype_bytes`, so the SAME budget fits wider tables — bf16
#: halves the cut width pressure, int8 quarters it.
BYTES_PER_CELL = 4

#: enumeration guard: a cut whose joint assignment space exceeds this
#: many lanes is declared unplannable (the sizing error names it).
MAX_CUT_LANES = 4096


class MemboundError(ValueError):
    """Memory-bounded planning failed: no cut set within the lane
    budget brings the peak table under ``max_util_bytes``.  The
    message reports the ACTIONABLE sizing — planned peak table bytes
    vs the budget and the cut width reached — instead of a retry
    hint."""

    def __init__(
        self,
        *,
        naive_peak_bytes: int,
        reached_peak_bytes: int,
        max_util_bytes: int,
        cut_width: int,
        lanes: int,
        max_cut_lanes: int,
    ):
        self.naive_peak_bytes = naive_peak_bytes
        self.reached_peak_bytes = reached_peak_bytes
        self.max_util_bytes = max_util_bytes
        self.cut_width = cut_width
        self.lanes = lanes
        super().__init__(
            "memory-bounded planning failed: naive peak contraction "
            f"table is {naive_peak_bytes} bytes against "
            f"max_util_bytes={max_util_bytes}; a cut of width "
            f"{cut_width} reaches a {reached_peak_bytes}-byte peak "
            f"but needs {lanes} enumeration lanes "
            f"(> max_cut_lanes={max_cut_lanes}).  Raise "
            "max_util_bytes, raise max_cut_lanes, or reduce the "
            "instance's induced width (order='min_fill' narrows "
            "loopy graphs)."
        )


# -- cross-edge consistency (pre-plan domain pruning) --------------------


def prune_plan(plan: ContractionPlan):
    """Shrink the plan's domains by hard-constraint consistency, IN
    PLACE: a value of ``v`` is pruned when some single part forces
    every completion to ``+inf`` energy (generalized arc consistency
    over the part's scope, iterated to fixpoint so one variable's
    pruning propagates across shared — cross — edges).  Pruned values
    are optimal for no query: they never enter a finite optimum and
    weigh ``exp(-inf) = 0`` in any logsumexp, so every registered ⊕
    is exact on the pruned plan.  A domain is never emptied (a fully
    infeasible instance keeps its semantics: all-``-inf`` sweeps).

    Returns ``(pruned_cells, keep, orig_len)``: the number of table
    cells removed across the plan's buckets, the per-variable
    original-index arrays of the surviving values, and the original
    domain lengths (marginal results scatter back through these)."""
    domains = plan.domains
    orig_len = {v: len(domains[v]) for v in domains}
    parts = [
        (scope, table)
        for v in plan.order
        for (scope, table) in plan.buckets[v]
    ]
    inf_parts = [
        (scope, table)
        for scope, table in parts
        if np.isposinf(table).any()
    ]
    keep = {
        v: np.arange(orig_len[v], dtype=np.intp) for v in domains
    }
    if not inf_parts:
        return 0, keep, orig_len

    alive = {
        v: np.ones(orig_len[v], dtype=bool) for v in domains
    }
    changed = True
    while changed:
        changed = False
        for scope, table in inf_parts:
            masked = np.asarray(table, dtype=np.float64)
            for ax, u in enumerate(scope):
                a = alive[u]
                if not a.all():
                    shp = [1] * len(scope)
                    shp[ax] = a.size
                    masked = np.where(a.reshape(shp), masked, np.inf)
            for ax, u in enumerate(scope):
                other = tuple(
                    i for i in range(len(scope)) if i != ax
                )
                support = (
                    np.min(masked, axis=other) if other else masked
                )
                # ONLY +inf support is infeasible: a -inf support is
                # an infinitely GOOD completion (±inf is a legitimate
                # hard-constraint cost — docs/faults.md), and pruning
                # it would delete the optimum
                dead = alive[u] & np.isposinf(support)
                if dead.any() and (alive[u] & ~dead).any():
                    alive[u][dead] = False
                    changed = True

    if all(a.all() for a in alive.values()):
        return 0, keep, orig_len
    keep = {v: np.flatnonzero(alive[v]) for v in domains}
    pruned_cells = 0
    for v in plan.order:
        new_bucket = []
        for scope, table in plan.buckets[v]:
            before = table.size
            t = table
            for ax, u in enumerate(scope):
                if keep[u].size != orig_len[u]:
                    t = np.take(t, keep[u], axis=ax)
            pruned_cells += before - t.size
            new_bucket.append((scope, t))
        plan.buckets[v] = new_bucket
        if plan.wbuckets[v]:
            # weight (log-prob) parts ride the same domains — slice
            # them too or an expectation lane misaligns its axes
            new_w = []
            for scope, t in plan.wbuckets[v]:
                for ax, u in enumerate(scope):
                    if keep[u].size != orig_len[u]:
                        t = np.take(t, keep[u], axis=ax)
                new_w.append((scope, t))
            plan.wbuckets[v] = new_w
    for v in list(domains):
        if keep[v].size != orig_len[v]:
            domains[v] = [domains[v][i] for i in keep[v]]
    return pruned_cells, keep, orig_len


# -- the cut-set planner -------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CutPlan:
    """One instance's cut decision at one budget (a pure function of
    the plan's structure/domains and the budget — what makes OOM
    re-planning deterministic)."""

    cut: Tuple[str, ...]
    n_lanes: int
    budget_cells: int
    naive_peak_cells: int
    bounded_peak_cells: int
    #: bytes per SCALAR-WORLD cell = BYTES_PER_CELL × the semiring's
    #: cell width (a kbest:8 sweep moves 8 f32s per table cell — the
    #: budget model must see them or the sweep lands 8× over budget)
    cell_width: int = 1
    #: storage dtype of the device tables this cut was budgeted for —
    #: bf16 halves and int8 quarters the per-cell byte width, so the
    #: same ``max_util_bytes`` fits more cells (a smaller cut)
    table_dtype: str = "f32"
    #: table format the sweep will run at: ``"sparse"`` sizes
    #: hard-capped nodes at their estimated PACKED cells (feasible
    #: fraction × box, plus the per-candidate index overhead), so a
    #: 0.9-sparse table fits ~10× more scope under the same budget
    table_format: str = "dense"

    @property
    def width(self) -> int:
        return len(self.cut)

    @property
    def bytes_per_cell(self) -> int:
        return table_dtype_bytes(self.table_dtype) * max(
            int(self.cell_width), 1
        )


def plan_cut(
    plan: ContractionPlan,
    max_util_bytes: int,
    pad=None,
    max_cut_lanes: int = MAX_CUT_LANES,
    cell_width: int = 1,
    table_dtype: str = "f32",
    table_format: str = "dense",
) -> CutPlan:
    """Choose a minimal cut set keeping every contraction table of
    the plan under ``max_util_bytes``.

    Dims-only simulation (no tables): each node's target is its
    separator plus its own axis; conditioning a variable collapses
    its axis to 1 in EVERY table that carries it.  Sizes are taken
    on the level-pack lattice of the active ``pad`` policy
    (``ops/padding.py:bucket_util_shape`` — identity under
    ``NO_PADDING``, and conditioned size-1 axes always stay 1), so
    the budget caps what the device will actually ALLOCATE per lane,
    not the pre-padding cell count.  Greedy pick, from the remaining
    oversized nodes: the variable occurring in the most oversized
    targets — a variable shared across sibling subtrees bounds all
    of them with ONE enumeration (the RMB-DPOP reuse) — tie-broken
    root-most (latest elimination position: ancestors near the root
    sit in the most separators), then by name.  Deterministic: a
    pure function of (graph, domains, budget, pad).  Raises
    :class:`MemboundError` when no cut within ``max_cut_lanes``
    enumeration lanes meets the budget.

    ``cell_width`` is the semiring's structured-cell width
    (``ops/semiring.py``): every table cell is ``cell_width`` f32s on
    device, so the cell budget divides by it — a ``kbest:8`` sweep
    under ``max_util_bytes`` must not land 8× over budget unseen.

    ``table_dtype`` is the device storage dtype of the sweep's tables
    (``ops/padding.py:as_table_dtype``): the budget divides by the
    REAL per-cell byte width, so the same ``max_util_bytes`` fits 2×
    the cells at bf16 and 4× at int8 — a strictly smaller (or equal)
    cut than f32 for the same plan and budget.

    ``table_format="sparse"`` sizes each scalar-cell node at what the
    sparse sweep will actually allocate: the node's feasible fraction
    (min over its own tables of the non-``+inf`` share — the packed
    support can only be smaller) times the dense box, times the
    per-candidate overhead factor ``(value + index bytes) / value
    bytes``.  Nodes too dense or too small to pack keep their dense
    size, so the estimate is format-aware per node, not a blanket
    discount.  Conditioning keeps the unconditioned feasible
    fraction (the per-slice density varies around it) — the OOM
    replan ladder of :func:`run_bounded` absorbs underestimates."""
    from pydcop_tpu.ops.padding import NO_PADDING, bucket_util_shape
    from pydcop_tpu.ops.sparse import (
        SPARSE_INDEX_BYTES,
        SPARSE_MAX_DENSITY,
        SPARSE_MIN_CELLS,
        as_table_format,
    )

    pad = NO_PADDING if pad is None else pad
    table_dtype = as_table_dtype(table_dtype)
    table_format = as_table_format(table_format)
    # structured cells never pack (ops/semiring.py gates on scalar
    # kinds), so a kbest/expectation sweep sizes dense regardless
    sparse = table_format == "sparse" and int(cell_width) <= 1
    feas: Dict[str, float] = {}
    sp_factor = 1.0
    if sparse:
        vb = table_dtype_bytes(table_dtype)
        sp_factor = (vb + SPARSE_INDEX_BYTES) / vb
        for v in plan.order:
            f = 1.0
            for _dims, t in plan.buckets.get(v, ()):
                a = np.asarray(t)
                if a.size:
                    f = min(
                        f, 1.0 - float(np.isposinf(a).mean())
                    )
            feas[v] = f
    bytes_per_cell = table_dtype_bytes(table_dtype) * max(
        int(cell_width), 1
    )
    budget_cells = max(int(max_util_bytes) // bytes_per_cell, 1)
    seps: Dict[str, List[str]] = {}
    targets: Dict[str, List[str]] = {}
    for v in plan.order:
        seps[v] = plan.sep_of(v, seps)
        targets[v] = seps[v] + [v]
    dsize = {
        v: bucket_util_shape((len(plan.domains[v]),), pad)[0]
        for v in plan.domains
    }

    def sizes(cutset):
        out = []
        for v, tgt in targets.items():
            size = 1
            for d in tgt:
                size *= 1 if d in cutset else dsize[d]
            if sparse:
                f = feas.get(v, 1.0)
                est = size * f * sp_factor
                if (
                    f <= SPARSE_MAX_DENSITY
                    and size >= SPARSE_MIN_CELLS
                    and est < size
                ):
                    size = max(int(np.ceil(est)), 1)
            out.append((v, tgt, size))
        return out

    naive_peak = max((s for _, _, s in sizes(frozenset())), default=1)
    cut: List[str] = []
    cutset: set = set()
    lanes = 1
    while True:
        oversized = [
            (v, tgt, s)
            for v, tgt, s in sizes(cutset)
            if s > budget_cells
        ]
        if not oversized:
            break
        counts: Dict[str, int] = {}
        for _, tgt, _ in oversized:
            for d in tgt:
                if d not in cutset and dsize[d] > 1:
                    counts[d] = counts.get(d, 0) + 1
        # an oversized node (> budget_cells >= 1) always has an
        # unconditioned multi-value dim, so counts is never empty
        pick = min(
            counts,
            key=lambda d: (-counts[d], -plan.pos[d], d),
        )
        if lanes * dsize[pick] > max_cut_lanes:
            reached = max(
                (s for _, _, s in sizes(cutset)), default=1
            )
            raise MemboundError(
                naive_peak_bytes=naive_peak * bytes_per_cell,
                reached_peak_bytes=reached * bytes_per_cell,
                max_util_bytes=int(max_util_bytes),
                cut_width=len(cut),
                lanes=lanes * dsize[pick],
                max_cut_lanes=max_cut_lanes,
            )
        cut.append(pick)
        cutset.add(pick)
        lanes *= dsize[pick]
    bounded_peak = max((s for _, _, s in sizes(cutset)), default=1)
    return CutPlan(
        tuple(cut), lanes, budget_cells, naive_peak, bounded_peak,
        cell_width=max(int(cell_width), 1), table_dtype=table_dtype,
        table_format=table_format,
    )


def lane_plans(plan: ContractionPlan, cut: Sequence[str]):
    """Expand a plan into its cut-assignment lanes: one conditioned
    :class:`ContractionPlan` per joint assignment of ``cut``, cut
    domains shrunk to singletons with their table axes KEPT at
    length 1 — every lane has identical shapes, which is what lets
    lanes share level-pack buckets (and compiled kernels) with each
    other.  Returns ``(plans, combos)``; an empty cut returns the
    plan itself (no copies)."""
    if not cut:
        return [plan], [()]
    combos = list(
        itertools.product(
            *(range(len(plan.domains[c])) for c in cut)
        )
    )
    out = []
    for combo in combos:
        fixed = dict(zip(cut, combo))
        domains_l = dict(plan.domains)
        for c, i in fixed.items():
            domains_l[c] = [plan.domains[c][i]]

        def _slice(bucket):
            lane_parts = []
            for scope, table in bucket:
                t = table
                for d in scope:
                    if d in fixed:
                        t = np.take(
                            t, [fixed[d]], axis=scope.index(d)
                        )
                lane_parts.append((scope, t))
            return lane_parts

        buckets_l: Dict[str, list] = {}
        wbuckets_l: Dict[str, list] = {}
        for v in plan.order:
            buckets_l[v] = _slice(plan.buckets[v])
            wbuckets_l[v] = _slice(plan.wbuckets[v])
        out.append(
            ContractionPlan(
                domains_l, plan.order, buckets_l,
                plan.const_energy, plan.order_name,
                wbuckets=wbuckets_l,
                node_semiring=plan.node_semiring,
                max_vars=plan.max_vars,
            )
        )
    return out, combos


# -- the budgeted sweep driver -------------------------------------------


class BoundedSweep:
    """Result of one budgeted merged sweep over K instances' lanes.

    ``sw`` is the underlying :class:`~pydcop_tpu.ops.semiring._Sweep`
    whose instance axis is the FLAT lane list; ``ranges[k]`` slices
    instance ``k``'s lanes out of it.  Combination helpers implement
    the per-⊕ cross-lane contracts (module docstring)."""

    __slots__ = (
        "sw", "plans", "cuts", "ranges", "lanes", "combos", "keep",
        "orig_len", "replans", "budget_bytes", "max_util_bytes",
        "pruned_cells", "on_device",
    )

    def __init__(
        self, sw, plans, cuts, ranges, lanes, combos, keep,
        orig_len, replans, budget_bytes, max_util_bytes,
        pruned_cells, on_device,
    ):
        self.sw = sw
        self.plans = plans
        self.cuts = cuts
        self.ranges = ranges
        self.lanes = lanes  # flat lane plans (sweep instance axis)
        self.combos = combos
        self.keep = keep
        self.orig_len = orig_len
        self.replans = replans
        self.budget_bytes = budget_bytes
        self.max_util_bytes = max_util_bytes
        self.pruned_cells = pruned_cells
        self.on_device = on_device

    # -- per-instance views ---------------------------------------------

    def lane_values(self, k: int) -> List[float]:
        """Raw per-lane aggregates (root total + applied shifts) —
        the caller folds ``const_energy``/``beta`` in."""
        lo, hi = self.ranges[k]
        return [
            self.sw.root_total[l] + self.sw.total_shift[l]
            for l in range(lo, hi)
        ]

    def lane_errs(self, k: int) -> List[float]:
        lo, hi = self.ranges[k]
        return [
            sum(
                self.sw.err[l].get(r, 0.0)
                for r in self.lanes[l].roots
            )
            for l in range(lo, hi)
        ]

    def best_lane(self, k: int, maximize: bool) -> int:
        """GLOBAL index of instance ``k``'s winning lane under an
        idempotent ⊕ (first best wins ties — deterministic)."""
        lo, _ = self.ranges[k]
        vals = self.lane_values(k)
        best = max(vals) if maximize else min(vals)
        return lo + vals.index(best)

    def logsumexp_lanes(self, k: int) -> Tuple[float, float]:
        """Cross-lane ⊕-combine for logsumexp: the combined value
        and its bound — worst lane bound (multiplicative-error
        argument, module docstring) plus the f64 combine rounding."""
        vals = np.asarray(self.lane_values(k), dtype=np.float64)
        errs = self.lane_errs(k)
        combined = float(_np_logsumexp(vals))
        err = max(errs, default=0.0) + _EPS64 * (len(errs) + 2)
        return combined, err

    def stats(self, k: int) -> Dict[str, int]:
        lo, hi = self.ranges[k]
        sw = self.sw
        return {
            "cells": sum(sw.cells[lo:hi]),
            "dispatches": sum(sw.dispatches[lo:hi]),
            "device_nodes": sum(sw.device_nodes[lo:hi]),
            "host_nodes": sum(sw.host_nodes[lo:hi]),
        }

    def width(self, k: int) -> int:
        lo, _ = self.ranges[k]
        return max(
            (len(s) for s in self.sw.seps[lo].values()), default=0
        )

    def meta(self, k: int) -> Dict[str, Any]:
        """The ``result["membound"]`` block.  ``on_device`` is true
        only when the device was still allowed at the final budget
        AND at least one of this instance's contractions actually
        dispatched — an ``auto``-mode sweep whose bounded tables all
        fell below ``device_min_cells`` truthfully reports False."""
        cp = self.cuts[k]
        return {
            "max_util_bytes": int(self.max_util_bytes),
            "budget_bytes": int(self.budget_bytes),
            "on_device": bool(
                self.on_device and self.stats(k)["device_nodes"] > 0
            ),
            "cut": list(cp.cut),
            "cut_width": cp.width,
            "cut_lanes": cp.n_lanes,
            "table_dtype": cp.table_dtype,
            "table_format": cp.table_format,
            "peak_table_bytes": cp.bounded_peak_cells
            * cp.bytes_per_cell,
            "naive_peak_table_bytes": cp.naive_peak_cells
            * cp.bytes_per_cell,
            "pruned_cells": int(self.pruned_cells),
            "replans": int(self.replans),
        }


def run_bounded(
    plans: Sequence[ContractionPlan],
    sr: Semiring,
    *,
    max_util_bytes: int,
    beta: float = 1.0,
    device_min_cells: Optional[int] = 1 << 14,
    pad=None,
    tol: float = 1e-6,
    max_table_size: int = 1 << 26,
    want_args: bool = False,
    max_cut_lanes: int = MAX_CUT_LANES,
    t0: Optional[float] = None,
    timeout: Optional[float] = None,
    bnb: str = "off",
    table_dtype: str = "f32",
    table_format: str = "dense",
) -> Optional[BoundedSweep]:
    """Prune, plan, and run ONE budgeted merged sweep over K
    instances (module docstring), re-planning at half the budget on
    device OOM until the plan fits or the device is abandoned for
    bounded host f64.  Returns the :class:`BoundedSweep`, or None on
    timeout; raises :class:`MemboundError` when the USER's budget is
    itself unplannable (replan budgets that become unplannable fall
    to the host instead of raising — the caller asked for THAT
    budget, and the original plan still bounds host memory).

    ``bnb`` threads the branch-and-bound pruned kernels through the
    budgeted sweep: each cut LANE is an instance of the merged
    sweep, so the pruning context — greedy incumbent, rest bounds,
    shift ledger — is built PER LANE from the lane's conditioned
    plan (a lane is an independent subproblem, so pruning against
    its own incumbent is exact per lane and the cross-lane ⊕-combine
    is untouched).  ``plan_cut``'s byte sizing ignores the mask by
    construction: pruning changes which rows are WORKED, never what
    the device allocates."""
    from pydcop_tpu.engine.supervisor import DeviceOOMError
    from pydcop_tpu.ops.padding import NO_PADDING
    from pydcop_tpu.telemetry import get_metrics, get_tracer

    met = get_metrics()
    tracer = get_tracer()
    from pydcop_tpu.ops.sparse import as_table_format

    pad = NO_PADDING if pad is None else pad
    table_dtype = as_table_dtype(table_dtype)
    table_format = as_table_format(table_format)
    t0 = time.perf_counter() if t0 is None else t0
    if int(max_util_bytes) <= 0:
        raise ValueError(
            f"max_util_bytes must be > 0, got {max_util_bytes}"
        )

    pruned_cells = 0
    keep: List[Dict[str, np.ndarray]] = []
    orig_len: List[Dict[str, int]] = []
    for p in plans:
        pc, kp, ol = prune_plan(p)
        pruned_cells += pc
        keep.append(kp)
        orig_len.append(ol)
    if met.enabled and pruned_cells:
        met.inc("membound.pruned_cells", pruned_cells)

    # the user's budget must be plannable — this is the actionable
    # sizing error (peak bytes vs budget, cut width), replacing the
    # old "try order='min_fill'" retry hint for budgeted calls
    cuts0 = [
        plan_cut(
            p, max_util_bytes, pad, max_cut_lanes,
            cell_width=sr.cell_width, table_dtype=table_dtype,
            table_format=table_format,
        )
        for p in plans
    ]
    cuts = cuts0
    budget = int(max_util_bytes)
    dmc = device_min_cells
    replans = 0
    while True:
        flat: List[ContractionPlan] = []
        ranges: List[Tuple[int, int]] = []
        combos: List[list] = []
        for p, c in zip(plans, cuts):
            lps, cbs = lane_plans(p, c.cut)
            ranges.append((len(flat), len(flat) + len(lps)))
            flat.extend(lps)
            combos.append(cbs)
        try:
            sw = contract_sweep(
                flat, sr, beta=beta, device_min_cells=dmc, pad=pad,
                tol=tol, max_table_size=max_table_size,
                want_args=want_args, t0=t0, timeout=timeout,
                on_oom="raise" if dmc is not None else "host",
                bnb=bnb, table_dtype=table_dtype,
                table_format=table_format,
            )
        except DeviceOOMError:
            # the replan rung of the OOM ladder: level->node already
            # degraded inside the sweep; a per-node OOM means the
            # TABLES are too big, and only a tighter plan changes that
            replans += 1
            if met.enabled:
                met.inc("membound.replans")
            budget //= 2
            next_cuts = None
            if budget >= 2 * table_dtype_bytes(table_dtype):
                try:
                    next_cuts = [
                        plan_cut(
                            p, budget, pad, max_cut_lanes,
                            cell_width=sr.cell_width,
                            table_dtype=table_dtype,
                            table_format=table_format,
                        )
                        for p in plans
                    ]
                except MemboundError:
                    next_cuts = None
            if tracer.enabled:
                tracer.event(
                    "membound-replan", cat="supervisor",
                    budget_bytes=budget,
                    to_host=next_cuts is None,
                )
            if next_cuts is not None:
                cuts = next_cuts
                continue
            # bottom of the ladder: abandon the device.  Host f64 at
            # the ORIGINAL budget's plan — memory stays bounded, and
            # host contractions cannot OOM the accelerator.
            dmc = None
            budget = int(max_util_bytes)
            cuts = cuts0
            continue
        if sw is None:
            return None
        bs = BoundedSweep(
            sw, list(plans), cuts, ranges, flat, combos, keep,
            orig_len, replans, budget, int(max_util_bytes),
            pruned_cells, dmc is not None,
        )
        if met.enabled:
            # a gauge, not a counter: widths of successive budgeted
            # calls must not SUM into a meaningless total (the
            # per-result ``membound`` block carries exact values)
            met.gauge(
                "membound.cut_width",
                max((c.width for c in cuts), default=0),
            )
            met.inc(
                "membound.cut_lanes",
                sum(c.n_lanes for c in cuts),
            )
        return bs


def combine_marginals(
    bs: BoundedSweep,
    k: int,
    sr: Semiring,
    beta: float,
    t0: float,
    timeout: Optional[float],
) -> Optional[Dict[str, np.ndarray]]:
    """Cross-lane marginal combine for instance ``k``:
    ``p(x_v) = Σ_l w_l · p_l(x_v)`` with lane weights
    ``w_l ∝ exp(agg_l)`` (each lane's downward pass runs on host f64
    as in the unbudgeted sweep), scattered back over the ORIGINAL
    domain — pruned values carry exactly probability 0, and a cut
    variable's marginal is the normalized lane-weight mass of each of
    its conditioned values.  Returns None on timeout."""
    from pydcop_tpu.ops.semiring import _downward_marginals

    lo, hi = bs.ranges[k]
    plan = bs.plans[k]
    cut = list(bs.cuts[k].cut)
    vals = np.asarray(bs.lane_values(k), dtype=np.float64)
    m = float(np.max(vals))
    if np.isfinite(m):
        w = np.exp(vals - m)
    else:  # every lane fully infeasible: weight lanes uniformly
        w = np.ones(len(vals))
    w = w / w.sum()

    keep = bs.keep[k]
    full: Dict[str, np.ndarray] = {
        v: np.zeros(bs.orig_len[k][v]) for v in plan.domains
    }
    for j, l in enumerate(range(lo, hi)):
        margs = _downward_marginals(
            bs.lanes[l], bs.sw, l, sr, beta, t0, timeout
        )
        if margs is None:
            return None
        combo = bs.combos[k][j]
        for v, p in margs.items():
            if v in cut:
                i_pruned = combo[cut.index(v)]
                full[v][keep[v][i_pruned]] += float(w[j])
            else:
                full[v][keep[v]] += w[j] * np.asarray(p)
    return full


# -- memory-bounded DPOP (min/+ through the same machinery) --------------


def solve_dpop_bounded(
    dcop,
    params: Dict[str, Any],
    *,
    timeout: Optional[float] = None,
    pad_policy: Any = None,
    max_table_size: int = 1 << 26,
) -> Dict[str, Any]:
    """Memory-bounded exact DPOP: ``build_plan`` over the pseudo-tree
    order (DPOP's own bucket tree), the budgeted min/+ sweep with the
    arg certificate per lane, a VALUE phase on the winning lane, and
    the DPOP-shaped result dict plus a ``membound`` block.  The
    entry ``algorithms/dpop.py:solve_host`` delegates to when
    ``max_util_bytes > 0``."""
    from pydcop_tpu.ops.padding import as_pad_policy
    from pydcop_tpu.ops.semiring import (
        MIN_SUM,
        _value_phase,
        as_bnb,
        build_plan,
    )
    from pydcop_tpu.ops.sparse import as_table_format as _as_fmt

    # the unbudgeted UTIL phase's own knob resolution — one mapping,
    # or the budgeted path could silently drift off the
    # bit-identical-to-unbounded contract
    from pydcop_tpu.algorithms.dpop import _resolve_device_min_cells

    t0 = time.perf_counter()
    max_util_bytes = int(params.get("max_util_bytes", 0) or 0)
    pad = as_pad_policy(pad_policy)
    dmc = _resolve_device_min_cells(params)

    plan = build_plan(dcop, order="pseudo_tree")
    t_util = time.perf_counter()
    bs = run_bounded(
        [plan], MIN_SUM,
        max_util_bytes=max_util_bytes,
        device_min_cells=dmc, pad=pad, want_args=True,
        max_table_size=max_table_size, t0=t0, timeout=timeout,
        bnb=as_bnb(params.get("bnb"), "auto"),
        table_dtype=as_table_dtype(params.get("table_dtype")),
        table_format=_as_fmt(params.get("table_format")),
    )
    if bs is None:
        return _dpop_timeout(dcop, t0)
    util_time = time.perf_counter() - t_util

    winner = bs.best_lane(0, maximize=False)
    t_value = time.perf_counter()
    assignment = _value_phase(bs.lanes[winner], bs.sw.args[winner])
    cost = dcop.solution_cost(assignment)
    from pydcop_tpu.telemetry import get_tracer

    tracer = get_tracer()
    if tracer.enabled:
        tracer.add_span(
            "value-phase", "phase", t_value,
            time.perf_counter() - t_value, algo="dpop",
        )
    stats = bs.stats(0)
    n_lanes = bs.cuts[0].n_lanes
    n_msgs = sum(
        1 for v in plan.order if plan.parent[v] is not None
    )
    height = max(plan.height.values(), default=0)
    return {
        "assignment": assignment,
        "cost": cost,
        "final_assignment": assignment,
        "final_cost": cost,
        "cycle": height,
        # one bounded UTIL + one VALUE message per non-root node per
        # cut lane — the MB-DPOP accounting
        "msg_count": 2 * n_msgs * n_lanes,
        "msg_size": stats["cells"] + n_msgs * n_lanes,
        "status": "finished",
        "time": time.perf_counter() - t0,
        "cost_trace": [cost],
        "util_time": util_time,
        "util_backend": "device" if bs.on_device else "host",
        "util_cells": stats["cells"],
        "util_device_nodes": stats["device_nodes"],
        "util_host_nodes": stats["host_nodes"],
        "util_dispatches": stats["dispatches"],
        "membound": bs.meta(0),
    }


def _dpop_timeout(dcop, t0: float) -> Dict[str, Any]:
    return {
        "assignment": {},
        "cost": None,
        "final_assignment": {},
        "final_cost": None,
        "cycle": 0,
        "msg_count": 0,
        "msg_size": 0,
        "status": "timeout",
        "time": time.perf_counter() - t0,
        "cost_trace": [],
    }
