"""Core jitted cost kernels over a :class:`CompiledProblem`.

These functions are the hot path shared by the whole local-search
family (DSA/A-DSA, MGM/MGM-2, DBA/GDBA), Max-Sum's variable-side
aggregation, and cost reporting:

- :func:`segment_sum_edges` — sum a per-edge quantity into per-variable
  rows (+ ``psum`` across the mesh when sharded).
- :func:`local_cost_sweep` — every variable's full candidate-value cost
  row under the current assignment (the batched equivalent of the
  reference's per-agent ``compute_cost`` loops).
- :func:`total_cost` — solution cost of an assignment, on device.
- :func:`neighbor_gather` — gather a per-variable quantity from each
  primal-graph neighbor (the batched equivalent of neighbor messages).

All are pure, shape-static, and fuse into a handful of XLA kernels
(gathers + segment-sum).  When ``axis_name`` is given they are running
inside ``shard_map`` with the problem's edge/constraint arrays sharded
over that mesh axis; the only collective is a ``psum`` of the
[n_vars, d] (or scalar) accumulator, which rides ICI.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from pydcop_tpu.ops.compile import CompiledProblem

# Single-shard per-variable aggregations on the CPU backend switch
# from the TPU-shaped per-slot prefix gathers to one segment-sum above
# this many edges.  Measured (round 3, Max-Sum belief + local-search
# sweep): segment-sum wins at EVERY size on CPU (1.5x at 200 vars to
# 6.9x at 1M), so the default is 0 (always on CPU).  The TPU keeps
# gathers — segment_sum lowers to scatter-add there, the
# worst-profiled shape.  tests/test_perf_guard.py raises this to pin
# the TPU lowering.
CPU_SEGMENT_MIN_EDGES = 0


def use_cpu_segment_path(problem: "CompiledProblem") -> bool:
    """True when a SINGLE-SHARD per-variable aggregation should take
    the CPU segment-sum lowering instead of the TPU gather shape —
    the one dispatch switch shared by every aggregation call site."""
    return (
        jax.default_backend() == "cpu"
        and problem.n_edges >= CPU_SEGMENT_MIN_EDGES
    )


def segment_sum_edges(
    problem: CompiledProblem,
    per_edge: jax.Array,
    axis_name: Optional[str] = None,
) -> jax.Array:
    """Sum per-edge rows into per-variable rows: [E, ...] → [n_vars, ...].

    Backend-aware like ``maxsum.belief_from_r``: the TPU single-shard
    path gathers via the compiler's padded per-variable incoming-edge
    lists (XLA scatters / ``segment_sum`` cost ~6× a same-size gather
    there, BASELINE.md round-1 notes); the CPU single-shard path takes
    one ``segment_sum`` (contiguous writes beat the cache-missing
    gather loop — same round-3 measurement series as Max-Sum's
    belief).  Sharded path: edges are mesh-local so the replicated
    global edge lists don't apply; segment-sum + ``psum``.
    """
    if axis_name is None and not use_cpu_segment_path(problem):
        pad = jnp.zeros(
            (1,) + per_edge.shape[1:], dtype=per_edge.dtype
        )
        padded = jnp.concatenate([per_edge, pad], axis=0)
        ve = problem.var_edges
        n = ve.shape[0]
        # per-slot PREFIX gathers: variables are compiled degree-
        # descending (ops/compile.py var_slot_counts), so slot p's
        # real entries are rows [0, counts[p]) — gathering only those
        # cuts the element count from n·max_deg to Σ deg(v).  The
        # gather is element-bound on TPU (BASELINE.md round 3), so
        # this is the lever.
        counts = problem.var_slot_counts or (n,) * ve.shape[1]
        acc = jnp.zeros(
            (n,) + per_edge.shape[1:], dtype=per_edge.dtype
        )
        for p in range(ve.shape[1]):
            n_p = min(counts[p], n)
            if n_p == 0:
                break  # counts are monotone over slots
            g = padded[ve[:n_p, p]]
            if n_p < n:
                g = jnp.pad(
                    g, ((0, n - n_p),) + ((0, 0),) * (g.ndim - 1)
                )
            acc = acc + g
        return acc
    out = jax.ops.segment_sum(
        per_edge, problem.edge_var, num_segments=problem.n_vars
    )
    if axis_name is not None:
        out = jax.lax.psum(out, axis_name)
    return out


def local_cost_sweep(
    problem: CompiledProblem,
    values: jax.Array,
    axis_name: Optional[str] = None,
) -> jax.Array:
    """f32[n_vars, d_max]: cost of each candidate value for each
    variable, holding all other variables at ``values``.

    local_cost[v, x] = unary[v, x]
                     + Σ_{c ∋ v} c(x, values of other scope vars)

    Padded values carry BIG (from ``unary``), so argmin stays in-domain.
    """
    # base index of each edge's constraint cell with co-vars fixed
    co_vals = values[problem.edge_covars]  # [E, k_max-1]
    base = problem.edge_offset + jnp.sum(
        co_vals * problem.edge_costrides, axis=1
    )  # [E]
    d = problem.d_max
    cells = base[:, None] + jnp.arange(d)[None, :] * problem.edge_stride[:, None]
    sweeps = problem.tables_flat[cells]  # [E, d]
    summed = segment_sum_edges(problem, sweeps, axis_name)
    return summed + problem.unary


def total_cost(
    problem: CompiledProblem,
    values: jax.Array,
    axis_name: Optional[str] = None,
) -> jax.Array:
    """Scalar cost of a full assignment (compiled sign: always a
    minimization cost; callers re-negate for max problems)."""
    scope_vals = values[problem.con_scopes]  # [C, k_max]
    cell = problem.con_offset + jnp.sum(
        scope_vals * problem.con_strides, axis=1
    )
    con_cost = jnp.sum(problem.tables_flat[cell]) if problem.n_cons else 0.0
    if axis_name is not None:
        con_cost = jax.lax.psum(con_cost, axis_name)
    var_cost = jnp.sum(
        jnp.take_along_axis(
            problem.unary, values[:, None], axis=1
        )[:, 0]
    )
    return con_cost + var_cost


def neighbor_gather(
    problem: CompiledProblem, quantity: jax.Array, fill: float = 0.0
) -> jax.Array:
    """[n_vars, max_deg(, ...)]: ``quantity`` gathered from each primal
    neighbor, with ``fill`` on padding slots.

    ``quantity`` is [n_vars] or [n_vars, ...]; the gather broadcasts
    over trailing dims.  Only valid when the neighbor arrays are
    replicated (they are: neighbor structure is per-variable, and
    variables are replicated across the mesh).
    """
    g = quantity[problem.neighbors]  # [n, max_deg, ...]
    mask = problem.neighbor_mask
    mask = mask.reshape(mask.shape + (1,) * (g.ndim - 2))
    return jnp.where(mask, g, fill)
