"""Core jitted cost kernels over a :class:`CompiledProblem`.

These three functions are the hot path shared by the whole local-search
family (DSA/A-DSA, MGM/MGM-2, DBA/GDBA) and by cost reporting:

- :func:`local_cost_sweep` — every variable's full candidate-value cost
  row under the current assignment (the batched equivalent of the
  reference's per-agent ``compute_cost`` loops).
- :func:`total_cost` — solution cost of an assignment, on device.
- :func:`neighbor_gather` — gather a per-variable quantity from each
  primal-graph neighbor (the batched equivalent of neighbor messages).

All are pure, shape-static, and fuse into a handful of XLA kernels
(gathers + segment-sum).  No pallas needed here: the ops are
bandwidth-bound gathers XLA already handles well on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pydcop_tpu.ops.compile import CompiledProblem


def local_cost_sweep(
    problem: CompiledProblem, values: jax.Array
) -> jax.Array:
    """f32[n_vars, d_max]: cost of each candidate value for each
    variable, holding all other variables at ``values``.

    local_cost[v, x] = unary[v, x]
                     + Σ_{c ∋ v} c(x, values of other scope vars)

    Padded values carry BIG (from ``unary``), so argmin stays in-domain.
    """
    # base index of each edge's constraint cell with co-vars fixed
    co_vals = values[problem.edge_covars]  # [E, k_max-1]
    base = problem.edge_offset + jnp.sum(
        co_vals * problem.edge_costrides, axis=1
    )  # [E]
    d = problem.d_max
    cells = base[:, None] + jnp.arange(d)[None, :] * problem.edge_stride[:, None]
    sweeps = problem.tables_flat[cells]  # [E, d]
    summed = jax.ops.segment_sum(
        sweeps, problem.edge_var, num_segments=problem.n_vars
    )
    return summed + problem.unary


def total_cost(problem: CompiledProblem, values: jax.Array) -> jax.Array:
    """Scalar cost of a full assignment (compiled sign: always a
    minimization cost; callers re-negate for max problems)."""
    scope_vals = values[problem.con_scopes]  # [C, k_max]
    cell = problem.con_offset + jnp.sum(
        scope_vals * problem.con_strides, axis=1
    )
    con_cost = jnp.sum(problem.tables_flat[cell]) if problem.n_cons else 0.0
    var_cost = jnp.sum(
        jnp.take_along_axis(
            problem.unary, values[:, None], axis=1
        )[:, 0]
    )
    return con_cost + var_cost


def neighbor_gather(
    problem: CompiledProblem, quantity: jax.Array, fill: float = 0.0
) -> jax.Array:
    """[n_vars, max_deg(, ...)]: ``quantity`` gathered from each primal
    neighbor, with ``fill`` on padding slots.

    ``quantity`` is [n_vars] or [n_vars, ...]; the gather broadcasts
    over trailing dims.
    """
    g = quantity[problem.neighbors]  # [n, max_deg, ...]
    mask = problem.neighbor_mask
    mask = mask.reshape(mask.shape + (1,) * (g.ndim - 2))
    return jnp.where(mask, g, fill)
