"""Semiring-generic contraction core — one device engine for
optimization, marginals, and counting (``docs/semirings.md``).

DPOP's join+project+argmin, Max-Sum's factor marginalization, and
SyncBB's bound evaluation are all instances of ONE functional
aggregate query: a semiring contraction over an elimination order
(FAQ, arXiv:1504.04044; "Juggling Functions Inside a Database",
arXiv:1703.03147).  This module factors that query out of the
per-algorithm kernels:

- a :class:`Semiring` registry — ``min/+`` (exact optimization:
  today's DPOP UTIL join), ``max/+`` (MAP, i.e. ``max/×`` in
  log-space), ``+/×`` via stable logsumexp (weighted counting — the
  partition function ``log Z``), and ``+/×`` with per-message
  normalization (marginal inference).  Everything operates in the
  LOG DOMAIN, where ``⊗`` is ``+`` — so every kernel is the same
  broadcast-add join with only the ``⊕`` projection swapped;
- :func:`contraction_kernel` — the jitted device kernel for one
  ``(joined shape, aligned part shapes)`` bucket, cached per
  SEMIRING so swapping ``⊕`` on the same shape bucket compiles at
  most one new executable (the level-pack keys themselves are
  shape-only and shared — ``tools/recompile_guard.py:
  run_semiring_guard`` pins this);
- pluggable elimination orders (:func:`build_plan`):
  ``"pseudo_tree"`` — the DFS order today's DPOP uses — and
  ``"min_fill"`` — the classic greedy width heuristic, often much
  narrower on loopy graphs;
- :func:`run_infer_many` — the merged multi-instance contraction
  sweep behind ``api.infer``/``api.infer_many``: waves by node
  height, device-eligible contractions bucketed across instances by
  level-pack key (``ops/padding.py:util_level_key``) and dispatched
  as ONE vmapped kernel per bucket, exactly the machinery the
  level-synchronous DPOP sweep built (``docs/performance.md``), with
  every device dispatch routed through the ambient supervisor
  (``engine/supervisor.py``).

Precision contract, per ``⊕``:

- **Idempotent ⊕ (min, max)** — the f32 exactness CERTIFICATE
  generalizes: the device returns only the arg-reduce plus each
  cell's decision margin; a margin ≥ 2·(#parts+1)·eps32·Σmax|part|
  proves the f32 arg equals the true arg, near-ties are repaired on
  host, and the projected values are re-evaluated on host in exact
  f64 at the certified arg — results are EXACT at any depth (the
  DPOP scheme, ``algorithms/dpop.py``).
- **logsumexp ⊕** — there is no arg to certify: the VALUE is the
  answer, so the engine does error-BOUND ACCOUNTING instead.  Each
  contraction carries an accumulated log-domain error bound
  (children's bounds + the local f32 join/reduction rounding); a
  contraction whose bound would exceed ``tol`` runs on host f64
  (counted as ``semiring.logsumexp_repairs``), and the result
  reports the final bound as ``error_bound``.  With the default
  ``tol=1e-6`` small problems run entirely in host f64; loosening
  ``tol`` buys device throughput at a known, reported cost.

This module is numpy-only at import (jax loads inside the kernel
builder, like ``algorithms/dpop.py``) so the API/CLI surfaces stay
jax-free (``tests/test_import_time.py``); ``pydcop_tpu.ops``
re-exports it lazily (PEP 562).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pydcop_tpu.ops.padding import (
    NO_PADDING,
    PadPolicy,
    as_pad_policy,
    pad_util_parts,
    stack_bucket,
    util_level_key,
)

_EPS32 = float(np.finfo(np.float32).eps)
_EPS64 = float(np.finfo(np.float64).eps)


# -- the semiring registry ---------------------------------------------


def _np_logsumexp(a: np.ndarray, axis=None, keepdims: bool = False):
    """Stable host-f64 logsumexp: max-shifted, and an all-``-inf``
    slice reduces to ``-inf`` (no ``nan`` from ``-inf - -inf``)."""
    a = np.asarray(a, dtype=np.float64)
    m = np.max(a, axis=axis, keepdims=True)
    m = np.where(np.isfinite(m), m, 0.0)
    with np.errstate(divide="ignore"):  # log(0) = -inf is the
        # correct, expected reduce of an all--inf slice
        out = np.log(
            np.sum(np.exp(a - m), axis=axis, keepdims=True)
        ) + m
    if not keepdims:
        out = np.squeeze(
            out, axis=tuple(range(a.ndim)) if axis is None else axis
        )
    return out


@dataclasses.dataclass(frozen=True)
class Semiring:
    """One ``(⊕, ⊗)`` pair in LOG-DOMAIN representation (``⊗ = +``).

    ``idempotent`` ⊕ (min/max) supports an arg-reduce and the f32
    exactness certificate; non-idempotent ⊕ (logsumexp) uses
    error-bound accounting instead.  ``normalize`` marks the
    marginal-inference variant whose messages are shift-normalized
    (the shifts are tracked, so absolute aggregates like ``log Z``
    are still recovered exactly).
    """

    name: str
    idempotent: bool
    maximize: bool = False  # direction of an idempotent ⊕
    normalize: bool = False
    doc: str = ""

    # -- algebra (log domain) ------------------------------------------

    @property
    def plus_identity(self) -> float:
        """Identity of ``⊕`` — also the annihilator of ``⊗``."""
        if self.idempotent and not self.maximize:
            return float(np.inf)
        return float(-np.inf)

    @property
    def times_identity(self) -> float:
        """Identity of ``⊗`` (log-domain ``+``)."""
        return 0.0

    def add(self, a, b):
        """Elementwise ``⊕`` (host f64) — the axiom-test primitive."""
        if self.idempotent:
            return (np.maximum if self.maximize else np.minimum)(a, b)
        return _np_logsumexp(np.stack([a, b]), axis=0)

    def combine(self, a, b):
        """Elementwise ``⊗`` (host f64): ``+`` in the log domain."""
        return np.asarray(a, dtype=np.float64) + np.asarray(
            b, dtype=np.float64
        )

    def reduce(self, a, axis=None, keepdims: bool = False):
        """``⊕``-projection over ``axis`` (host f64)."""
        if self.idempotent:
            fn = np.max if self.maximize else np.min
            return fn(a, axis=axis, keepdims=keepdims)
        return _np_logsumexp(a, axis=axis, keepdims=keepdims)

    def arg_reduce(self, a, axis: int = -1):
        """Argmin/argmax over ``axis`` — idempotent ⊕ only."""
        if not self.idempotent:
            raise ValueError(
                f"semiring {self.name!r}: ⊕ is not idempotent — there "
                "is no arg to reduce to"
            )
        return (np.argmax if self.maximize else np.argmin)(a, axis=axis)

    def shift_of(self, a: np.ndarray) -> float:
        """Message-normalization offset: the value subtracted from an
        outgoing message (min for ``min/+`` — DPOP's normalization —
        max otherwise, which is also the logsumexp stability shift)."""
        if a.size == 0:
            return 0.0
        if self.idempotent and not self.maximize:
            return float(a.min())
        return float(a.max())

    # -- traced (jnp) variants for use inside compiled steps -----------

    def jnp_reduce(self, a, axis, keepdims: bool = False):
        """``⊕``-projection inside a jax trace (``bp_factor_messages``
        and the contraction kernels)."""
        import jax.numpy as jnp

        if self.idempotent:
            fn = jnp.max if self.maximize else jnp.min
            return fn(a, axis=axis, keepdims=keepdims)
        m = jnp.max(a, axis=axis, keepdims=True)
        m = jnp.where(jnp.isfinite(m), m, 0.0)
        out = (
            jnp.log(jnp.sum(jnp.exp(a - m), axis=axis, keepdims=True))
            + m
        )
        return out if keepdims else jnp.squeeze(out, axis=axis)


SEMIRINGS: Dict[str, Semiring] = {}


def register_semiring(sr: Semiring) -> Semiring:
    """Add a semiring to the registry (``get_semiring`` name lookup)."""
    SEMIRINGS[sr.name] = sr
    return sr


def get_semiring(name: str) -> Semiring:
    if isinstance(name, Semiring):
        return name
    try:
        return SEMIRINGS[name]
    except KeyError:
        raise ValueError(
            f"unknown semiring {name!r} (registered: "
            f"{sorted(SEMIRINGS)})"
        )


MIN_SUM = register_semiring(
    Semiring(
        "min_sum", idempotent=True, maximize=False,
        doc="exact optimization over costs — DPOP's UTIL join",
    )
)
MAX_SUM = register_semiring(
    Semiring(
        "max_sum", idempotent=True, maximize=True,
        doc="MAP over log-weights (max/x in log space)",
    )
)
LOG_SUM_EXP = register_semiring(
    Semiring(
        "log_sum_exp", idempotent=False,
        doc="weighted counting: partition function log Z (+/x via "
        "stable logsumexp)",
    )
)
MARGINALS = register_semiring(
    Semiring(
        "marginals", idempotent=False, normalize=True,
        doc="+/x with message normalization — marginal inference",
    )
)

# query name (api.infer) -> the semiring its sweep runs on
QUERY_SEMIRINGS = {
    "map": "max_sum",
    "log_z": "log_sum_exp",
    "marginals": "marginals",
}


# -- device kernels -----------------------------------------------------
#
# One jitted join+projection per (semiring, joined shape, aligned part
# shapes) bucket.  The level-pack KEY is shape-only and shared across
# semirings (ops/padding.py:util_level_key), so swapping the semiring
# on the same problem bucket reuses the bucketing and compiles at most
# one new executable per semiring — zero on repeat
# (tools/recompile_guard.py:run_semiring_guard).  LRU-bounded for the
# same reason the DPOP join-kernel cache was: long-lived processes
# must not retain one executable per distinct shape forever.

_KERNELS: Dict[Tuple, Any] = {}
_KERNELS_MAX = 256


def contraction_kernel(
    sr: Semiring,
    shape: Tuple[int, ...],
    part_shapes: Tuple[Tuple[int, ...], ...],
    batched: bool = False,
):
    """Jit-compiled semiring contraction for one bucket: broadcast-add
    join of the aligned parts, then the ``⊕``-projection over the own
    (last) axis.  ``batched=True`` vmaps it over a leading stack axis.

    Idempotent ⊕ returns ``(arg, margins)`` — the exactness-
    certificate outputs; the projected values are NOT shipped back
    (the caller re-evaluates them exactly on host at the certified
    arg, so the transfer would be dead).  For ``min_sum`` this is
    bit-for-bit the historical DPOP join kernel
    (``algorithms/dpop.py:_join_kernel`` now delegates here).
    Non-idempotent ⊕ returns ``(values,)`` — a max-shifted f32
    logsumexp whose rounding is covered by the caller's error-bound
    accounting.
    """
    sr = get_semiring(sr)
    key = (sr.name, tuple(shape), tuple(part_shapes), batched)
    fn = _KERNELS.get(key)
    if fn is not None:
        return fn
    if len(_KERNELS) >= _KERNELS_MAX:
        _KERNELS.pop(next(iter(_KERNELS)))
    import jax
    import jax.numpy as jnp

    if sr.idempotent:
        if sr.maximize:

            def contract(*tabs):
                j = jnp.zeros(shape, dtype=jnp.float32)
                for t in tabs:
                    j = j + t  # aligned: broadcast over missing axes
                u = jnp.max(j, axis=-1)
                arg = jnp.argmax(j, axis=-1)
                if shape[-1] == 1:
                    margins = jnp.full(shape[:-1], jnp.inf)
                else:
                    one_hot = (
                        jnp.arange(shape[-1]) == arg[..., None]
                    )
                    second = jnp.max(
                        jnp.where(one_hot, -jnp.inf, j), axis=-1
                    )
                    margins = u - second
                return arg, margins

        else:

            def contract(*tabs):
                j = jnp.zeros(shape, dtype=jnp.float32)
                for t in tabs:
                    j = j + t  # aligned: broadcast over missing axes
                u = jnp.min(j, axis=-1)
                amin = jnp.argmin(j, axis=-1)
                if shape[-1] == 1:
                    margins = jnp.full(shape[:-1], jnp.inf)
                else:
                    # second best via masking the arg cell (exact; no
                    # sort)
                    one_hot = (
                        jnp.arange(shape[-1]) == amin[..., None]
                    )
                    second = jnp.min(
                        jnp.where(one_hot, jnp.inf, j), axis=-1
                    )
                    margins = second - u
                # values are NOT returned: the caller re-evaluates
                # them exactly on host at the certified arg
                return amin, margins

    else:

        def contract(*tabs):
            j = jnp.zeros(shape, dtype=jnp.float32)
            for t in tabs:
                j = j + t
            m = jnp.max(j, axis=-1)
            safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
            s = jnp.sum(jnp.exp(j - safe_m[..., None]), axis=-1)
            # an all--inf row (impossible configuration, or a padded
            # ghost guard row) stays -inf instead of going nan
            vals = jnp.where(
                jnp.isfinite(m), safe_m + jnp.log(s), m
            )
            return (vals,)

    from pydcop_tpu.telemetry.jit import profiled_jit

    fn = profiled_jit(
        jax.vmap(contract) if batched else contract,
        label=f"semiring-{sr.name}",
    )
    _KERNELS[key] = fn
    return fn


def bp_factor_messages(
    sr: Semiring,
    tab,
    q_pos: Sequence,
    mdt,
) -> list:
    """Factor→variable belief-propagation messages for one arity
    bucket, as a semiring contraction inside a jax trace.

    The standard sum-then-subtract marginalization:
    ``S = table ⊗ ⊗_p q_p`` (broadcast-add over the bucket's axes),
    ``M_p = ⊕`` over all axes but ``p``, ``r_p = M_p − q_p``,
    shift-normalized per edge.  With ``sr=min_sum`` this is bit-for-
    bit Max-Sum's factor phase (``algorithms/maxsum.py`` step 2 now
    delegates here); other semirings turn the same wiring into
    sum-product (marginal BP) or max-product message passing.

    ``tab`` is the bucket's ``[d, ..., d, m]`` table stack (f32),
    ``q_pos`` the ``k`` per-position ``[d, m]`` incoming messages
    (message dtype ``mdt`` — bf16 upcasts on the add), and the
    returned list holds the ``k`` outgoing ``[d, m]`` messages in
    ``mdt``.
    """
    import jax.numpy as jnp

    sr = get_semiring(sr)
    k = len(q_pos)
    d = q_pos[0].shape[0]
    m = q_pos[0].shape[1]
    s = tab  # [d, ..., d, m] — f32; mdt q upcasts on the add
    for p in range(k):
        shape = (1,) * p + (d,) + (1,) * (k - 1 - p) + (m,)
        s = s + q_pos[p].astype(tab.dtype).reshape(shape)
    outs = []
    for p in range(k):
        axes = tuple(a for a in range(k) if a != p)
        mp = sr.jnp_reduce(s, axes)  # [d, m]
        rp = mp - q_pos[p].astype(tab.dtype)
        # shift-normalize per edge (bounded over cycles): min for
        # min/+ — the historical Max-Sum normalization — max for the
        # maximizing/summing semirings
        if sr.idempotent and not sr.maximize:
            rp = rp - jnp.min(rp, axis=0, keepdims=True)
        else:
            rp = rp - jnp.max(rp, axis=0, keepdims=True)
        outs.append(rp.astype(mdt))
    return outs


# -- elimination orders and contraction plans ---------------------------


ELIMINATION_ORDERS = ("pseudo_tree", "min_fill")


def min_fill_order(
    domains: Dict[str, Sequence],
    scopes: Sequence[Sequence[str]],
    deadline: Optional[float] = None,
) -> List[str]:
    """Greedy min-fill elimination order over the primal graph: at
    each step eliminate the variable whose removal adds the fewest
    fill edges among its remaining neighbors (ties: smallest
    neighborhood, then name — deterministic).  The classic width
    heuristic; on loopy graphs it is often far narrower than the DFS
    pseudo-tree order.

    Fill counts are cached and invalidated INCREMENTALLY — a count
    changes only for the eliminated variable's neighbors and for the
    common neighbors of each added fill edge — so the selection loop
    is O(n) per step instead of recomputing every count
    (recompute-everything measured ~20s at just 800 vars; this stays
    sub-second at that size).  Dense graphs can still be slow —
    ``deadline`` (a ``perf_counter`` timestamp) raises
    ``TimeoutError`` between steps so an ``infer(timeout=...)``
    cannot hang inside plan construction."""
    adj: Dict[str, set] = {v: set() for v in domains}
    for scope in scopes:
        sc = [v for v in scope if v in adj]
        for a in sc:
            for b in sc:
                if a != b:
                    adj[a].add(b)
    remaining = {v: set(ns) for v, ns in adj.items()}
    order: List[str] = []
    cache: Dict[str, int] = {}

    def fill_count(v: str) -> int:
        ns = list(remaining[v])
        cnt = 0
        for i in range(len(ns)):
            ri = remaining[ns[i]]
            for j in range(i + 1, len(ns)):
                if ns[j] not in ri:
                    cnt += 1
        return cnt

    while remaining:
        if deadline is not None and time.perf_counter() > deadline:
            raise TimeoutError(
                f"min_fill elimination order timed out with "
                f"{len(remaining)} of {len(adj)} variables left"
            )
        best_key = None
        best = None
        for x in remaining:
            c = cache.get(x)
            if c is None:
                c = cache[x] = fill_count(x)
            key = (c, len(remaining[x]), x)
            if best_key is None or key < best_key:
                best_key, best = key, x
        v = best
        order.append(v)
        ns = list(remaining[v])
        # invalidation set: v's neighbors (their neighborhoods change)
        # plus, per added fill edge (a, b), every common neighbor of
        # a and b (the pair stops counting as missing for them)
        dirty = set(ns)
        for i in range(len(ns)):
            for j in range(i + 1, len(ns)):
                a, b = ns[i], ns[j]
                if b not in remaining[a]:
                    remaining[a].add(b)
                    remaining[b].add(a)
                    dirty |= remaining[a] & remaining[b]
        for n in ns:
            remaining[n].discard(v)
        del remaining[v]
        cache.pop(v, None)
        for x in dirty:
            cache.pop(x, None)
    return order


class ContractionPlan:
    """One instance's bucket tree: the elimination order, per-variable
    buckets of owned ENERGY tables (f64, minimization convention —
    semiring transforms apply at sweep time so one plan serves every
    query), and the parent/children structure a dims-only simulation
    of the elimination derives.  ``const_energy`` accumulates
    fully-external (scope-free after slicing) parts — invisible to
    arg queries, a constant factor of ``Z``."""

    __slots__ = (
        "domains", "order", "pos", "buckets", "parent", "children",
        "roots", "height", "const_energy", "order_name",
    )

    def __init__(self, domains, order, buckets, const_energy, order_name):
        self.domains = domains
        self.order = order
        self.pos = {v: i for i, v in enumerate(order)}
        self.buckets = buckets
        self.const_energy = const_energy
        self.order_name = order_name
        # dims-only elimination simulation: the message scope of v is
        # the union of its bucket dims and its children's message
        # dims, minus v; its parent is the earliest-ELIMINATED
        # variable of that scope (the bucket the message lands in)
        self.parent: Dict[str, Optional[str]] = {}
        self.children: Dict[str, List[str]] = {v: [] for v in order}
        self.roots: List[str] = []
        msg_dims: Dict[str, set] = {}
        for v in order:
            dims: set = set()
            for scope, _ in buckets[v]:
                dims.update(scope)
            for c in self.children[v]:
                dims.update(msg_dims[c])
            dims.discard(v)
            msg_dims[v] = dims
            if dims:
                p = min(dims, key=self.pos.__getitem__)
                self.parent[v] = p
                self.children[p].append(v)
            else:
                self.parent[v] = None
                self.roots.append(v)
        # wave index = node HEIGHT (children resolve strictly earlier
        # waves; every leaf lands in wave 0 — the ragged-tree batching
        # property the level-sync DPOP sweep established)
        self.height: Dict[str, int] = {}
        for v in order:  # children precede parents in elim order
            self.height[v] = 1 + max(
                (self.height[c] for c in self.children[v]), default=-1
            )

    def sep_of(self, name: str, child_seps: Dict[str, List[str]]):
        """Separator of ``name``: dims of its own parts plus its
        children's separators, minus itself — sorted root-most first
        (descending elimination position), the axis convention every
        stored message uses."""
        dims: set = set()
        for scope, _ in self.buckets[name]:
            dims.update(scope)
        for c in self.children[name]:
            dims.update(child_seps[c])
        dims.discard(name)
        return sorted(dims, key=lambda v: -self.pos[v])

    def width(self) -> int:
        """Induced width: the largest separator the sweep will build
        (dims-only; cheap enough to report up front)."""
        seps: Dict[str, List[str]] = {}
        w = 0
        for v in self.order:
            seps[v] = self.sep_of(v, seps)
            w = max(w, len(seps[v]))
        return w


def build_plan(
    dcop,
    order: str = "pseudo_tree",
    deadline: Optional[float] = None,
) -> ContractionPlan:
    """Build the contraction plan for one DCOP under an elimination
    order heuristic.  ``deadline`` (a ``perf_counter`` timestamp)
    bounds the ``min_fill`` search — it raises ``TimeoutError``, which
    :func:`run_infer_many` turns into ``status="timeout"`` results.

    Tables are extracted ONCE as f64 energies (sign-folded for
    ``objective: max`` problems, external variables sliced out,
    variable value-costs folded in as unary parts — the same
    preparation DPOP's ``_prepare_instance`` performs); each part is
    owned by its earliest-eliminated scope variable, which under the
    ``pseudo_tree`` order reproduces DPOP's deepest-variable
    ownership exactly.
    """
    if order not in ELIMINATION_ORDERS:
        raise ValueError(
            f"unknown elimination order {order!r} (expected one of "
            f"{ELIMINATION_ORDERS})"
        )
    sign = -1.0 if dcop.objective == "max" else 1.0
    ext_values = {
        n: ev.value for n, ev in dcop.external_variables.items()
    }
    domains: Dict[str, list] = {
        v.name: list(v.domain.values) for v in dcop.variables.values()
    }

    parts: List[Tuple[List[str], np.ndarray]] = []
    const_energy = 0.0
    for v in dcop.variables.values():
        if v.has_cost:
            costs = np.array(
                [sign * v.cost_for_val(x) for x in v.domain.values],
                dtype=np.float64,
            )
            parts.append(([v.name], costs))
    for c in dcop.constraints.values():
        scope_ext = [n for n in c.scope_names if n in ext_values]
        if scope_ext:
            c = c.slice({n: ext_values[n] for n in scope_ext})
        scope = list(c.scope_names)
        m = c.as_matrix()
        table = sign * np.asarray(m.matrix, dtype=np.float64)
        if not scope:
            const_energy += float(table)
            continue
        parts.append((scope, table))

    if order == "min_fill":
        elim = min_fill_order(
            domains, [s for s, _ in parts], deadline=deadline
        )
    else:
        from pydcop_tpu.graphs import pseudotree as _pt

        graph = _pt.build_computation_graph(dcop)
        names = [
            n
            for root in graph.roots
            for n in graph.depth_first_order(root)
        ]
        # reverse DFS pre-order: children strictly before parents —
        # the elimination order whose bucket tree IS the pseudo-tree
        elim = list(reversed(names))

    pos = {v: i for i, v in enumerate(elim)}
    buckets: Dict[str, List[Tuple[List[str], np.ndarray]]] = {
        v: [] for v in elim
    }
    for scope, table in parts:
        owner = min(scope, key=pos.__getitem__)
        buckets[owner].append((scope, table))
    return ContractionPlan(domains, elim, buckets, const_energy, order)


# -- the merged contraction sweep ---------------------------------------


def _align(table, dims, target):
    """Jax-free broadcast alignment (the DPOP join primitive —
    ``algorithms/_tables.align_table``, imported lazily to keep ops/
    free of an algorithms/ import at module load)."""
    from pydcop_tpu.algorithms._tables import align_table

    return align_table(table, dims, target)


class _Sweep:
    """Per-call state of one merged upward sweep (K instances)."""

    __slots__ = (
        "msgs", "args", "root_total", "total_shift", "cells",
        "device_nodes", "host_nodes", "dispatches", "err", "seps",
    )

    def __init__(self, K: int):
        # msgs[k][name] = (sep, message f64, max|message|)
        self.msgs: List[Dict[str, tuple]] = [{} for _ in range(K)]
        self.args: List[Dict[str, tuple]] = [{} for _ in range(K)]
        self.seps: List[Dict[str, List[str]]] = [{} for _ in range(K)]
        self.root_total = [0.0] * K
        self.total_shift = [0.0] * K
        self.cells = [0] * K
        self.device_nodes = [0] * K
        self.host_nodes = [0] * K
        self.dispatches = [0] * K
        self.err = [
            {} for _ in range(K)
        ]  # name -> accumulated log-domain error bound


def contract_sweep(
    plans: Sequence[ContractionPlan],
    sr: Semiring,
    *,
    beta: float = 1.0,
    device_min_cells: Optional[int] = 1 << 14,
    pad: PadPolicy = NO_PADDING,
    level_sync: bool = True,
    tol: float = 1e-6,
    max_table_size: int = 1 << 26,
    want_args: bool = False,
    t0: Optional[float] = None,
    timeout: Optional[float] = None,
    on_oom: str = "host",
) -> Optional[_Sweep]:
    """Merged bottom-up contraction sweep over K instances.

    Wave ``w`` holds every instance's height-``w`` nodes;
    device-eligible contractions bucket by level-pack key ACROSS
    instances (``ops/padding.py:util_level_key``) and run as ONE
    vmapped :func:`contraction_kernel` dispatch per bucket under the
    ambient supervisor — the level-synchronous DPOP machinery with
    the ``⊕`` swapped.  Tables enter the sweep in KERNEL domain:
    energies for ``min_sum``, log-weights ``-beta·E`` otherwise.

    Per ``⊕``: idempotent contractions are certified + host-repaired
    (exact, ``want_args`` retains the arg tables for a MAP value
    phase); logsumexp contractions carry accumulated error bounds
    and fall back to host f64 when a device pass would push the
    bound past ``tol`` (``semiring.logsumexp_repairs``).  Returns
    the sweep state, or None on timeout.  Counters:
    ``semiring.contractions`` per node, ``semiring.dispatches`` per
    device dispatch.

    ``on_oom`` picks the bottom rung of the device-OOM ladder: a
    level stack that OOMs always degrades to per-node dispatches;
    a PER-NODE OOM then either redoes that node on host f64
    (``"host"``, the default) or raises the ``DeviceOOMError``
    (``"raise"`` — the budgeted sweeps of ``ops/membound.py``, which
    answer it by RE-PLANNING at a tighter ``max_util_bytes`` before
    abandoning the device).
    """
    from pydcop_tpu.engine.supervisor import (
        DeviceOOMError,
        get_supervisor,
    )
    from pydcop_tpu.telemetry import get_metrics, get_tracer

    met = get_metrics()
    tracer = get_tracer()
    sup = get_supervisor()
    t0 = time.perf_counter() if t0 is None else t0
    K = len(plans)
    sw = _Sweep(K)
    _key_memo: Dict[tuple, tuple] = {}

    def table_in(tbl: np.ndarray) -> np.ndarray:
        if sr.idempotent and not sr.maximize:
            return tbl  # min/+: raw energies (beta rescales argmins
            # by nothing and the magnitudes stay familiar)
        return (-beta) * tbl

    def finish(k, name, plan, sep, u, arg):
        if met.enabled:
            met.inc("semiring.contractions")
        if want_args:
            sw.args[k][name] = (sep, arg)
        if plan.parent[name] is None:
            # root: the reduce is a scalar — fold it into the
            # instance aggregate (plus every shift already applied)
            sw.root_total[k] += float(u)
        else:
            shift = sr.shift_of(u)
            if not np.isfinite(shift):
                shift = 0.0  # an all--inf message normalizes to itself
            u = u - shift
            sw.total_shift[k] += shift
            sw.msgs[k][name] = (
                sep, u, float(np.max(np.abs(u), initial=0.0))
            )
            sw.cells[k] += u.size

    def host_contract(k, name, plan, sep, target, shape, parts, err_in):
        j = np.zeros(shape, dtype=np.float64)
        for dims, table in parts:
            j = j + _align(table, dims, target)
        arg = sr.arg_reduce(j, axis=-1) if want_args else None
        u = sr.reduce(j, axis=-1)
        sw.host_nodes[k] += 1
        if not sr.idempotent:
            # f64 rounding of the same computation: negligible, but
            # accounted so the reported bound is never an understatement
            scale = max(
                sum(
                    float(np.max(np.abs(t), initial=0.0))
                    for _, t in parts
                ),
                1.0,
            )
            sw.err[k][name] = err_in + _EPS64 * (
                (len(parts) + 1) * scale + shape[-1] + 2
            )
        finish(k, name, plan, sep, u, arg)

    waves: List[List[Tuple[int, str]]] = []
    for k, plan in enumerate(plans):
        for n in plan.order:
            w = plan.height[n]
            while len(waves) <= w:
                waves.append([])
            waves[w].append((k, n))

    t_sweep = time.perf_counter()
    for wave in waves:
        buckets: Dict[tuple, list] = {}
        order: List[tuple] = []
        for k, name in wave:
            if timeout is not None and time.perf_counter() - t0 > timeout:
                return None
            plan = plans[k]
            domains = plan.domains
            sep = plan.sep_of(name, sw.seps[k])
            sw.seps[k][name] = sep
            target = sep + [name]
            shape = [len(domains[d]) for d in target]
            size = 1
            for s in shape:
                size *= s
            if size > max_table_size:
                raise ValueError(
                    f"contraction table for {name!r} needs {size} "
                    f"cells (separator {sep}); exceeds "
                    f"max_table_size={max_table_size}.  The induced "
                    f"width under order={plan.order_name!r} is too "
                    "large — try order='min_fill', or an approximate "
                    "(message-passing) algorithm."
                )
            # own parts PRE-SUMMED into one exact f64 part (the DPOP
            # trick: bitwise the same join, collapses leaf kernel
            # signatures, tightens the f32 bound), then children
            own_parts = plan.buckets[name]
            parts: List[Tuple[List[str], np.ndarray]] = []
            parts_max = 0.0
            err_in = 0.0
            if own_parts:
                odims: List[str] = []
                for dims, _ in own_parts:
                    odims.extend(d for d in dims if d not in odims)
                if len(own_parts) > 1:
                    o = np.zeros(
                        [len(domains[d]) for d in odims],
                        dtype=np.float64,
                    )
                    for dims, table in own_parts:
                        o = o + _align(
                            table_in(table), dims, odims
                        )
                else:
                    o = np.asarray(
                        table_in(own_parts[0][1]), dtype=np.float64
                    )
                    odims = list(own_parts[0][0])
                parts.append((odims, o))
                parts_max += float(np.max(np.abs(o), initial=0.0))
            for c in plan.children[name]:
                cdims, ctable, cmax = sw.msgs[k][c]
                parts.append((cdims, ctable))
                parts_max += cmax
                err_in += sw.err[k].get(c, 0.0)
            if not parts:
                # an isolated, cost-free variable: its contraction is
                # the reduce of a zero table over its own domain
                parts.append(([name], np.zeros(shape[-1])))

            dmc = device_min_cells
            use_device = dmc is not None and size >= dmc
            if use_device and not sr.idempotent:
                # error-budget gate: a device (f32) pass whose
                # accumulated bound would exceed tol runs on host f64
                # instead — the logsumexp analogue of the exactness
                # certificate (there is no arg to repair; the value
                # IS the answer)
                scale = max(parts_max, 1.0)
                local = _EPS32 * (
                    (len(parts) + 1) * scale + shape[-1] + 2
                )
                if err_in + local > tol:
                    use_device = False
                    if met.enabled:
                        met.inc("semiring.logsumexp_repairs")
            if not use_device:
                host_contract(
                    k, name, plan, sep, target, shape, parts, err_in
                )
                continue

            aligned = [
                _align(t, dims, target) for dims, t in parts
            ]
            raw = (
                tuple(shape), tuple(a.shape for a in aligned)
            )
            key = _key_memo.get(raw)
            if key is None:
                key = _key_memo[raw] = util_level_key(
                    raw[0], raw[1], pad
                )
            if key not in buckets:
                buckets[key] = []
                order.append(key)
            buckets[key].append(
                (
                    (k, name, sep, target, shape, parts,
                     parts_max, err_in),
                    aligned,
                )
            )

        # ghost guard over padded own-axis cells is the ⊕-identity:
        # +inf keeps a MIN arg-reduce inside the real domain; -inf is
        # absorbing for max AND contributes exp(-inf)=0 to a logsumexp
        guard = sr.plus_identity

        for key in order:
            entries = buckets[key]
            if timeout is not None and time.perf_counter() - t0 > timeout:
                return None
            pshape, part_shapes = key
            n_rows = len(entries)
            shape0 = entries[0][0][4]
            uniform = all(it[4] == shape0 for it, _ in entries)
            if level_sync and n_rows > 1 and uniform:
                ok = _dispatch_stacked(
                    sw, sr, entries, pshape, part_shapes, shape0,
                    pad, guard, tol, want_args, finish, sup, met,
                    plans,
                )
                if ok:
                    continue
                # OOM on the stacked dispatch: degrade to the
                # per-node path below (a single join that still OOMs
                # degrades further to the exact host contraction)
                if met.enabled:
                    met.inc("engine.oom_splits")
            fn = contraction_kernel(sr, pshape, part_shapes)
            for item, aligned in entries:
                (k, name, sep, target, shape, parts,
                 parts_max, err_in) = item
                if (
                    timeout is not None
                    and time.perf_counter() - t0 > timeout
                ):
                    return None
                # the ONE padding-contract implementation
                # (ops/padding.py): the mask is part of the kernel
                # signature exactly when the policy is enabled
                # (util_level_key), and the guard is this semiring's
                # ⊕-identity
                padded = pad_util_parts(
                    aligned, shape, pshape, guard=guard,
                    with_mask=pad.enabled,
                )
                try:
                    outs = sup.dispatch(
                        lambda p=padded: tuple(
                            np.asarray(x) for x in fn(*p)
                        ),
                        scope="semiring.node", width=1,
                        table_bytes=4 * int(np.prod(pshape)),
                    )
                except DeviceOOMError:
                    if on_oom == "raise":
                        raise
                    host_contract(
                        k, name, plans[k], sep, target, shape,
                        parts, err_in,
                    )
                    continue
                if met.enabled:
                    met.inc("semiring.dispatches")
                sw.dispatches[k] += 1
                region = tuple(slice(0, s) for s in shape[:-1])
                _finish_device_row(
                    sw, sr, plans[k], item, outs, region, tol,
                    want_args, finish,
                )
    if tracer.enabled:
        tracer.add_span(
            "semiring.contract", "phase", t_sweep,
            time.perf_counter() - t_sweep, semiring=sr.name,
            instances=K, cells=sum(sw.cells),
        )
    return sw


def _dispatch_stacked(
    sw, sr, entries, pshape, part_shapes, shape0, pad, guard, tol,
    want_args, finish, sup, met, plans,
) -> bool:
    """One vmapped dispatch for a uniform level-pack bucket.  Returns
    False on device OOM (caller degrades to per-node dispatches)."""
    from pydcop_tpu.engine.supervisor import DeviceOOMError

    n_rows = len(entries)
    stack_h = stack_bucket(n_rows) if pad.enabled else n_rows
    n_parts = len(part_shapes)
    has_mask = n_parts == len(entries[0][1]) + 1
    bufs = [
        np.zeros((stack_h,) + tuple(ps), dtype=np.float64)
        for ps in part_shapes
    ]
    for r, (item, aligned) in enumerate(entries):
        for i, a in enumerate(aligned):
            bufs[i][r][tuple(slice(0, s) for s in a.shape)] = a
        if has_mask:
            bufs[-1][r][..., shape0[-1]:] = guard
    fn = contraction_kernel(sr, pshape, part_shapes, batched=True)
    casts = [b.astype(np.float32) for b in bufs]
    try:
        outs = sup.dispatch(
            lambda: tuple(np.asarray(x) for x in fn(*casts)),
            scope="semiring.level", width=stack_h,
            table_bytes=4 * int(np.prod(pshape)),
        )
    except DeviceOOMError:
        return False
    if met.enabled:
        met.inc("semiring.dispatches")
    for k in sorted({item[0] for item, _ in entries}):
        sw.dispatches[k] += 1
    region_rows = tuple(slice(0, s) for s in shape0[:-1])
    for r, (item, aligned) in enumerate(entries):
        row_outs = tuple(o[r] for o in outs)
        _finish_device_row(
            sw, sr, plans[item[0]], item, row_outs, region_rows,
            tol, want_args, finish,
        )
    return True


def _finish_device_row(
    sw, sr, plan, item, outs, region, tol, want_args, finish
):
    """Certify / account one device contraction and finish the node.

    Idempotent ⊕: certify the f32 arg against the decision-margin
    bound, repair near-ties on host, re-evaluate the projected
    values in exact f64 at the certified arg (tie-heavy tables are
    redone wholesale on host — same contract as DPOP).  logsumexp ⊕:
    accept the f32 values and extend the accumulated error bound
    (the tol gate already ran before dispatch)."""
    from pydcop_tpu.telemetry import get_metrics

    met = get_metrics()
    (k, name, sep, target, shape, parts, parts_max, err_in) = item
    if sr.idempotent:
        arg, margins = outs
        arg = np.array(arg[region])  # writable (repair)
        margins = np.asarray(margins[region], dtype=np.float64)
        local_err = _EPS32 * (len(parts) + 1) * parts_max
        bad = np.argwhere(margins < 2.0 * local_err)
        if len(bad) * 10 > margins.size:
            # tie-heavy: per-cell repair would dominate — redo the
            # whole contraction on host f64 (still exact)
            if met.enabled:
                met.inc("semiring.cert_fallbacks")
            j = np.zeros(shape, dtype=np.float64)
            for dims, table in parts:
                j = j + _align(table, dims, target)
            u = sr.reduce(j, axis=-1)
            arg = sr.arg_reduce(j, axis=-1) if want_args else None
            sw.host_nodes[k] += 1
            finish(k, name, plan, sep, u, arg)
            return
        own = target[-1]
        for cell in map(tuple, bad):
            row = np.zeros(shape[-1], dtype=np.float64)
            for dims, table in parts:
                row += _cell_row(table, dims, target, cell)
            arg[cell] = int(sr.arg_reduce(row, axis=-1))
        # exact f64 values AT the certified arg: children contribute
        # zero error to their parents, whatever the tree depth
        grids = (
            np.indices(tuple(shape[:-1]), dtype=np.intp)
            if len(shape) > 1
            else None
        )
        u = np.zeros(tuple(shape[:-1]), dtype=np.float64)
        for dims, table in parts:
            idx = []
            for d in dims:
                if d == own:
                    idx.append(arg)
                else:
                    idx.append(grids[target.index(d)])
            u += np.asarray(table, dtype=np.float64)[tuple(idx)]
        sw.device_nodes[k] += 1
        finish(k, name, plan, sep, u, arg)
    else:
        (vals,) = outs
        u = np.asarray(vals[region], dtype=np.float64)
        scale = max(parts_max, 1.0)
        sw.err[k][name] = err_in + _EPS32 * (
            (len(parts) + 1) * scale + shape[-1] + 2
        )
        sw.device_nodes[k] += 1
        finish(k, name, plan, sep, u, None)


def _cell_row(table, dims, target, cell):
    """Exact f64 row of one part at a fixed separator cell (broadcast
    over the own axis when the part does not carry it)."""
    own = target[-1]
    idx = []
    for d in dims:
        if d == own:
            idx.append(slice(None))
        else:
            idx.append(cell[target.index(d)])
    row = np.asarray(table, dtype=np.float64)[tuple(idx)]
    if own not in dims:
        return np.full(1, float(row))
    return row


# -- queries ------------------------------------------------------------


def _value_phase(plan: ContractionPlan, args) -> Dict[str, Any]:
    """Top-down MAP value wave: condition each node's retained arg
    table on the accumulated ancestor assignment (parents precede
    children in reversed elimination order)."""
    assignment: Dict[str, Any] = {}
    idx: Dict[str, int] = {}
    for name in reversed(plan.order):
        sep, arg = args[name]
        best = int(arg[tuple(idx[d] for d in sep)])
        idx[name] = best
        assignment[name] = plan.domains[name][best]
    return assignment


def _downward_marginals(
    plan: ContractionPlan,
    sw: _Sweep,
    k: int,
    sr: Semiring,
    beta: float,
    t0: float,
    timeout: Optional[float],
) -> Optional[Dict[str, np.ndarray]]:
    """Host-f64 downward pass: outside-messages root→leaves, then each
    variable's normalized marginal.  Prefix/suffix child combines (no
    log-domain subtraction — ``-inf`` entries from hard constraints
    stay well-defined)."""
    down: Dict[str, Tuple[List[str], np.ndarray]] = {}
    marginals: Dict[str, np.ndarray] = {}

    def tin(tbl):
        return (-beta) * tbl

    for name in reversed(plan.order):  # parents before children
        if timeout is not None and time.perf_counter() - t0 > timeout:
            return None
        sep = sw.seps[k][name]
        target = sep + [name]
        shape = [len(plan.domains[d]) for d in target]
        base = np.zeros(shape, dtype=np.float64)
        for dims, table in plan.buckets[name]:
            base = base + _align(tin(table), dims, target)
        if name in down:
            ddims, dtable = down[name]
            base = base + _align(dtable, ddims, target)
        cs = plan.children[name]
        aligned_c = [
            _align(sw.msgs[k][c][1], sw.msgs[k][c][0], target)
            for c in cs
        ]
        # prefix[i] = ⊗ of children < i, suffix[i] = ⊗ of children >= i
        prefix = [np.zeros(shape, dtype=np.float64)]
        for a in aligned_c:
            prefix.append(prefix[-1] + a)
        suffix = [np.zeros(shape, dtype=np.float64)]
        for a in reversed(aligned_c):
            suffix.append(suffix[-1] + a)
        suffix.reverse()
        joint = base + prefix[-1]
        b = sr.reduce(joint, axis=tuple(range(len(sep)))) if sep else joint
        m = float(np.max(b)) if np.isfinite(np.max(b)) else 0.0
        p = np.exp(b - m)
        total = float(p.sum())
        marginals[name] = (
            p / total if total > 0 else np.full_like(p, 1.0 / p.size)
        )
        for i, c in enumerate(cs):
            excl = base + prefix[i] + suffix[i + 1]
            sep_c = sw.msgs[k][c][0]
            keep = set(sep_c)
            axes = tuple(
                ax for ax, d in enumerate(target) if d not in keep
            )
            d_c = sr.reduce(excl, axis=axes) if axes else excl
            shift = float(np.max(d_c))
            if np.isfinite(shift):
                d_c = d_c - shift
            down[c] = ([d for d in target if d in keep], d_c)
    return marginals


def run_infer_many(
    dcops: Sequence[Any],
    query: str,
    *,
    order: str = "pseudo_tree",
    beta: float = 1.0,
    tol: float = 1e-6,
    device: str = "auto",
    device_min_cells: int = 1 << 14,
    pad_policy: Any = None,
    max_table_size: int = 1 << 26,
    timeout: Optional[float] = None,
    max_util_bytes: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """Run one inference query over K instances with their contraction
    sweeps MERGED (the ``solve_many`` batching contract: same-bucket
    contractions from different instances share one vmapped dispatch
    and one compiled kernel; per-instance results are identical to
    sequential calls).  The engine behind ``api.infer`` /
    ``api.infer_many`` — callers own the telemetry session and
    supervisor installation.

    ``max_util_bytes`` runs the sweep MEMORY-BOUNDED
    (``ops/membound.py``): domains are consistency-pruned, every
    contraction table is kept under the budget by conditioning a cut
    set of variables, and the cut assignments ride the level-pack
    stack as extra vmapped lanes — exact results (per the query's ⊕
    contract) on instances whose naive tables dwarf device memory,
    at the cost of one sweep pass per cut lane.  The result carries
    a ``membound`` block (cut width/lanes, peak table bytes,
    replans).  An unplannable budget raises
    :class:`~pydcop_tpu.ops.membound.MemboundError`, which reports
    peak-table-bytes-vs-budget and the cut width reached — the
    actionable sizing, not a retry hint.

    Queries: ``"map"`` (max/+ — the exact MAP assignment, certified
    like DPOP), ``"log_z"`` (+/x — ``log Σ_x exp(-beta·E(x))``),
    ``"marginals"`` (+/x normalized — per-variable distributions
    ``p(x_v)``, plus ``log_z`` which the upward pass yields for
    free).
    """
    t0 = time.perf_counter()
    if query not in QUERY_SEMIRINGS:
        raise ValueError(
            f"unknown query {query!r} (expected one of "
            f"{sorted(QUERY_SEMIRINGS)})"
        )
    if device not in ("auto", "never", "always"):
        raise ValueError(
            f"device must be 'auto'|'never'|'always', got {device!r}"
        )
    if beta <= 0:
        raise ValueError(f"beta must be > 0, got {beta}")
    sr = get_semiring(QUERY_SEMIRINGS[query])
    pad = as_pad_policy(pad_policy)
    dmc: Optional[int]
    if device == "never":
        dmc = None
    elif device == "always":
        dmc = 0
    else:
        dmc = int(device_min_cells)

    from pydcop_tpu.telemetry import get_tracer

    tracer = get_tracer()
    K = len(dcops)
    deadline = None if timeout is None else t0 + timeout
    try:
        plans = [
            build_plan(d, order=order, deadline=deadline)
            for d in dcops
        ]
    except TimeoutError:
        # plan construction (the min_fill search) ate the budget —
        # same contract as a sweep timeout
        return [_timeout_result(query, t0) for _ in range(K)]
    want_args = query == "map"

    if max_util_bytes is not None:
        return _run_bounded_infer(
            dcops, plans, query, sr,
            max_util_bytes=int(max_util_bytes), beta=beta, dmc=dmc,
            pad=pad, tol=tol, max_table_size=max_table_size,
            want_args=want_args, t0=t0, timeout=timeout, K=K,
        )

    sw = contract_sweep(
        plans, sr, beta=beta, device_min_cells=dmc, pad=pad,
        tol=tol, max_table_size=max_table_size, want_args=want_args,
        t0=t0, timeout=timeout,
    )
    if sw is None:
        return [_timeout_result(query, t0) for _ in range(K)]

    results: List[Dict[str, Any]] = []
    for k, (dcop, plan) in enumerate(zip(dcops, plans)):
        agg = (
            sw.root_total[k]
            + sw.total_shift[k]
            - beta * plan.const_energy
        )
        # the instance bound is the sum over ROOT accumulations only:
        # each node's entry already chains its whole subtree via
        # err_in, so summing every node would count a leaf's local
        # error once per ancestor
        err = sum(sw.err[k].get(r, 0.0) for r in plan.roots)
        out: Dict[str, Any] = {
            "query": query,
            "semiring": sr.name,
            "order": plan.order_name,
            "status": "finished",
            "cells": sw.cells[k],
            "dispatches": sw.dispatches[k],
            "device_nodes": sw.device_nodes[k],
            "host_nodes": sw.host_nodes[k],
            # the sweep already derived every separator — don't re-run
            # the dims-only pass plan.width() would
            "width": max(
                (len(s) for s in sw.seps[k].values()), default=0
            ),
            "error_bound": err,
            "instances_batched": K,
        }
        if query == "map":
            assignment = _value_phase(plan, sw.args[k])
            cost = dcop.solution_cost(assignment)
            out["assignment"] = assignment
            out["cost"] = cost
            out["log_weight"] = agg
        elif query == "log_z":
            out["log_z"] = agg
        else:  # marginals
            t_down = time.perf_counter()
            margs = _downward_marginals(
                plan, sw, k, sr, beta, t0, timeout
            )
            if margs is None:
                results.append(_timeout_result(query, t0))
                continue
            if tracer.enabled:
                tracer.add_span(
                    "semiring.downward", "phase", t_down,
                    time.perf_counter() - t_down, semiring=sr.name,
                )
            out["marginals"] = {
                v: [float(x) for x in p] for v, p in margs.items()
            }
            out["log_z"] = agg
        out["time"] = (time.perf_counter() - t0) / K
        results.append(out)
    return results


def _timeout_result(query: str, t0: float) -> Dict[str, Any]:
    return {
        "query": query,
        "status": "timeout",
        "time": time.perf_counter() - t0,
    }


def _run_bounded_infer(
    dcops, plans, query, sr, *, max_util_bytes, beta, dmc, pad,
    tol, max_table_size, want_args, t0, timeout, K,
) -> List[Dict[str, Any]]:
    """Memory-bounded assembly behind :func:`run_infer_many`
    (``max_util_bytes`` set): the budgeted lane sweep
    (``ops/membound.py``) plus the per-⊕ cross-lane combines —
    idempotent ⊕ picks the best lane (exact), logsumexp ⊕-combines
    the lane values under the worst-lane error bound, marginals mix
    lane marginals by lane weight and scatter over the original
    (pre-pruning) domains."""
    from pydcop_tpu.ops import membound as _mb
    from pydcop_tpu.telemetry import get_tracer

    tracer = get_tracer()
    bs = _mb.run_bounded(
        plans, sr, max_util_bytes=max_util_bytes, beta=beta,
        device_min_cells=dmc, pad=pad, tol=tol,
        max_table_size=max_table_size, want_args=want_args,
        t0=t0, timeout=timeout,
    )
    if bs is None:
        return [_timeout_result(query, t0) for _ in range(K)]
    results: List[Dict[str, Any]] = []
    for k, (dcop, plan) in enumerate(zip(dcops, bs.plans)):
        const = beta * plan.const_energy
        out: Dict[str, Any] = {
            "query": query,
            "semiring": sr.name,
            "order": plan.order_name,
            "status": "finished",
            **bs.stats(k),
            "width": bs.width(k),
            "instances_batched": K,
            "membound": bs.meta(k),
        }
        if query == "map":
            winner = bs.best_lane(k, maximize=True)
            assignment = _value_phase(
                bs.lanes[winner], bs.sw.args[winner]
            )
            out["assignment"] = assignment
            out["cost"] = dcop.solution_cost(assignment)
            out["log_weight"] = (
                bs.lane_values(k)[winner - bs.ranges[k][0]] - const
            )
            out["error_bound"] = 0.0  # certified per lane, exact
        elif query == "log_z":
            v, err = bs.logsumexp_lanes(k)
            out["log_z"] = v - const
            out["error_bound"] = err
        else:  # marginals
            t_down = time.perf_counter()
            margs = _mb.combine_marginals(
                bs, k, sr, beta, t0, timeout
            )
            if margs is None:
                results.append(_timeout_result(query, t0))
                continue
            if tracer.enabled:
                tracer.add_span(
                    "semiring.downward", "phase", t_down,
                    time.perf_counter() - t_down, semiring=sr.name,
                )
            out["marginals"] = {
                v: [float(x) for x in p] for v, p in margs.items()
            }
            z, err = bs.logsumexp_lanes(k)
            out["log_z"] = z - const
            out["error_bound"] = err
        out["time"] = (time.perf_counter() - t0) / K
        results.append(out)
    return results
